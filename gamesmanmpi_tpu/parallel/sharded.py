"""The sharded level-synchronous solver (multi-device).

This is the TPU rebuild of the reference's distributed runtime proper
(src/process.py's cross-rank behavior, SURVEY.md §3.2-3.3 and §5.8):

  reference (per message/position)      here (per level, per shard)
  ------------------------------------  --------------------------------------
  comm.send(Job(LOOK_UP, child),        forward: expand locally, bucket all
     dest=hash(child) % world_size)     children by owner_shard(child), one
                                        lax.all_to_all over the ICI mesh,
                                        then sort-unique locally (dedup is
                                        local after owner routing)
  per-rank memo dict {pos: value}       per-shard sorted (states, cells)
                                        arrays — the hash-partitioned
                                        position table in sharded HBM
  SEND_BACK child result to parent      backward: all_gather the (tiny,
                                        transient) solved window of deeper
                                        levels, look child values up locally
  FINISHED broadcast                    backward loop reaching the root level

Capacity planning: all_to_all buffers are [num_shards, capacity] with
SENTINEL padding. Overflow (a shard receiving more than capacity from one
peer) is detected on host via returned per-destination counts and retried
with a doubled capacity — the "capacity counters + host-side spill loop
(rare path)" design of SURVEY.md §5.8.

Like the single-device engine, compiled steps are cached process-wide
(solve/engine._KERNELS via get_kernel) keyed on game identity, mesh devices
and shapes, and capacities are power-of-two buckets — re-instantiated solvers
reuse XLA executables, and the shape count stays O(log max-frontier).

Shard-count invariance (same tables for 1 and N shards) is the test contract
replacing the reference's `mpirun -np 1` vs `-np N` (SURVEY.md §4.2).
"""

from __future__ import annotations

import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gamesmanmpi_tpu.core.hashing import owner_shard, owner_shard_np
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame
from gamesmanmpi_tpu.ops.combine import combine_children
from gamesmanmpi_tpu.ops.dedup import sort_unique
from gamesmanmpi_tpu.ops.lookup import lookup_window
from gamesmanmpi_tpu.ops.padding import bucket_size
from gamesmanmpi_tpu.parallel.mesh import AXIS, make_mesh
from gamesmanmpi_tpu.solve.engine import (
    LevelTable,
    SolveResult,
    SolverError,
    canonical_scalar,
    get_kernel,
)


def _pad_shards(shard_arrays: List[np.ndarray], cap: int) -> np.ndarray:
    """Stack per-shard 1-D state arrays into [S, cap] with SENTINEL pad.

    The dtype (and sentinel) follows the input arrays' dtype.
    """
    from gamesmanmpi_tpu.core.bitops import sentinel_for

    S = len(shard_arrays)
    dtype = shard_arrays[0].dtype
    out = np.full((S, cap), sentinel_for(dtype), dtype=dtype)
    for s, arr in enumerate(shard_arrays):
        out[s, : arr.shape[0]] = arr
    return out


def _sharded_forward_step(game: TensorGame, S: int, route_cap: int, local):
    """Per-shard forward body: expand -> owner-bucket -> all_to_all -> dedup.

    local: [1, cap] this shard's frontier slice (shard_map gives the leading
    mesh axis). Returns ([1, S*route_cap] unique children, [1] count,
    [1, S] per-destination send counts for overflow detection).
    """
    sentinel = game.sentinel
    local = local[0]
    valid = local != sentinel
    prim = game.primitive(local)
    children, mask = game.expand(local)
    children = game.canonicalize(children)
    mask = mask & (valid & (prim == UNDECIDED))[:, None]
    flat = jnp.where(mask, children, sentinel).reshape(-1)
    owner = jnp.where(flat == sentinel, S, owner_shard(flat, S)).astype(
        jnp.int32
    )
    # Bucket by owner: stable-sort children by destination shard.
    order = jnp.argsort(owner, stable=True)
    s_owner = owner[order]
    s_kids = flat[order]
    # Position of each element within its destination bucket.
    first = jnp.searchsorted(s_owner, jnp.arange(S + 1))
    pos = jnp.arange(s_owner.shape[0]) - first[jnp.clip(s_owner, 0, S)]
    counts = first[1:] - first[:-1]  # per-destination send counts [S]
    out = jnp.full((S, route_cap), sentinel, dtype=local.dtype)
    # Out-of-range rows (owner==S) and overflow (pos>=route_cap) drop.
    out = out.at[s_owner, pos].set(s_kids, mode="drop")
    routed = jax.lax.all_to_all(out, AXIS, split_axis=0, concat_axis=0,
                                tiled=True)
    uniq, count = sort_unique(routed.reshape(-1))
    return uniq[None], count[None], counts[None]


def _sharded_backward_step(game: TensorGame, S: int, local, window_flat):
    """Per-shard backward body: expand -> all_gather window -> combine.

    window_flat: flat sequence of (states, values, remoteness) triples, one
    per window level, each [1, capL] shard slices.
    """
    sentinel = game.sentinel
    local = local[0]
    valid = local != sentinel
    prim = game.primitive(local)
    undecided = valid & (prim == UNDECIDED)
    children, mask = game.expand(local)
    children = game.canonicalize(children)
    mask = mask & undecided[:, None]
    children = jnp.where(mask, children, sentinel)
    # Gather the solved window from all shards; each shard's slice is
    # sorted, so lookups are per-chunk binary searches.
    tables = []
    for i in range(0, len(window_flat), 3):
        ts = jax.lax.all_gather(window_flat[i][0], AXIS)  # [S, capL]
        tv = jax.lax.all_gather(window_flat[i + 1][0], AXIS)
        tr = jax.lax.all_gather(window_flat[i + 2][0], AXIS)
        for s in range(S):
            tables.append((ts[s], tv[s], tr[s]))
    child_vals, child_rem, hit = lookup_window(children, tuple(tables))
    values, remoteness = combine_children(child_vals, child_rem, mask)
    values = jnp.where(undecided, values, jnp.where(valid, prim, UNDECIDED))
    remoteness = jnp.where(undecided, remoteness, 0)
    # Misses + zero-move UNDECIDED positions (see engine.resolve_level).
    misses = jnp.sum(mask & ~hit) + jnp.sum(undecided & ~jnp.any(mask, axis=-1))
    return values[None], remoteness[None], misses[None]


class ShardedSolver:
    """Hash-partitioned solver over a 1-D device mesh."""

    def __init__(
        self,
        game: TensorGame,
        *,
        num_shards: int | None = None,
        mesh=None,
        min_bucket: int = 256,
        paranoid: bool = False,
        logger=None,
        checkpointer=None,
    ):
        self.game = game
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        self.S = self.mesh.devices.shape[0]
        self.min_bucket = min_bucket
        self.paranoid = paranoid
        self.logger = logger
        self.checkpointer = checkpointer
        # Mesh identity participates in the process-wide kernel cache key
        # (same shard count over different device sets must not share).
        self._mesh_key = tuple(d.id for d in self.mesh.devices.flat)

    # ------------------------------------------------------------- jit builds

    def _forward_fn(self, cap: int, route_cap: int):
        """Compiled forward step: [S, cap] states -> routed unique children."""
        mesh, S = self.mesh, self.S

        def build(game):
            def per_shard(local):
                return _sharded_forward_step(game, S, route_cap, local)

            return jax.shard_map(
                per_shard,
                mesh=mesh,
                in_specs=P(AXIS),
                out_specs=(P(AXIS), P(AXIS), P(AXIS)),
            )

        return get_kernel(
            self.game, "sfwd", (self._mesh_key, cap, route_cap), build
        )

    def _backward_fn(self, cap: int, window_caps: tuple):
        """Compiled backward step for one level against a solved window."""
        mesh, S = self.mesh, self.S
        n_windows = len(window_caps)

        def build(game):
            def per_shard(local, *window_flat):
                return _sharded_backward_step(game, S, local, window_flat)

            return jax.shard_map(
                per_shard,
                mesh=mesh,
                in_specs=(P(AXIS),) + (P(AXIS),) * (3 * n_windows),
                out_specs=(P(AXIS), P(AXIS), P(AXIS)),
            )

        return get_kernel(
            self.game,
            "sbwd",
            (self._mesh_key, cap, tuple(window_caps)),
            build,
        )

    # ----------------------------------------------------------------- phases

    def _forward(self, pools: Dict[int, List[np.ndarray]], start_level: int):
        g = self.game
        S = self.S
        k = start_level
        while pools and k <= max(pools):
            if k not in pools:
                k += 1
                continue
            t0 = time.perf_counter()
            shards = pools[k]
            cap = bucket_size(max(a.shape[0] for a in shards), self.min_bucket)
            total = sum(a.shape[0] for a in shards)
            route_cap = bucket_size(
                max(64, 2 * cap * g.max_moves // S), self.min_bucket
            )
            stacked = _pad_shards(shards, cap)
            while True:
                uniq, count, send_counts = self._forward_fn(cap, route_cap)(
                    stacked
                )
                max_sent = int(np.asarray(send_counts).max())
                if max_sent <= route_cap:
                    break
                route_cap = bucket_size(max_sent)  # spill path: retry bigger
            uniq = np.asarray(uniq)
            count = np.asarray(count)
            # Children land in their levels' pools. For uniform unit-jump
            # games this is a single destination level; multi-jump games
            # compute each child's level host-side in one pass.
            for s in range(S):
                n = int(count[s])
                kids = uniq[s, :n]
                if n == 0:
                    continue
                if g.uniform_level_jump:
                    groups = [(k + 1, kids)]
                else:
                    kid_levels = np.asarray(
                        self._level_fn(bucket_size(n, self.min_bucket))(
                            jnp.asarray(_pad_shards([kids],
                                        bucket_size(n, self.min_bucket))[0])
                        )
                    )[:n]
                    groups = [
                        (int(lv), kids[kid_levels == lv])
                        for lv in np.unique(kid_levels)
                    ]
                for lv, batch in groups:
                    if lv not in pools:
                        pools[lv] = [np.empty(0, g.state_dtype)
                                     for _ in range(S)]
                    pools[lv][s] = np.union1d(pools[lv][s], batch)
            if self.logger is not None:
                self.logger.log(
                    {
                        "phase": "forward",
                        "level": k,
                        "frontier": total,
                        "shards": S,
                        "route_cap": route_cap,
                        "secs": time.perf_counter() - t0,
                    }
                )
            k += 1

    def _level_fn(self, cap: int):
        """Cached level_of kernel for multi-jump child grouping."""
        return get_kernel(
            self.game, "lvl", cap,
            lambda game: lambda states: jnp.where(
                states != game.sentinel, game.level_of(states), -1
            ),
        )

    def _repartition(self, states: np.ndarray) -> List[np.ndarray]:
        """Split a sorted global state array into per-shard sorted arrays."""
        owners = owner_shard_np(states, self.S)
        return [states[owners == s] for s in range(self.S)]

    def _backward(self, pools: Dict[int, List[np.ndarray]]):
        g = self.game
        S = self.S
        resolved: Dict[int, LevelTable] = {}
        padded_cache: Dict[int, tuple] = {}
        completed = (
            set(self.checkpointer.completed_levels())
            if self.checkpointer is not None
            else set()
        )
        for k in sorted(pools, reverse=True):
            t0 = time.perf_counter()
            shards = pools[k]
            cap = bucket_size(max(a.shape[0] for a in shards), self.min_bucket)
            stacked = _pad_shards(shards, cap)
            pv = np.full((S, cap), UNDECIDED, dtype=np.uint8)
            pr = np.zeros((S, cap), dtype=np.int32)
            from_checkpoint = k in completed
            if from_checkpoint:
                # Restart-from-level: reload the solved table, re-partition it
                # by owner to refill the per-shard window cache.
                table = self.checkpointer.load_level(k)
                table = LevelTable(
                    states=np.asarray(table.states, dtype=g.state_dtype),
                    values=table.values,
                    remoteness=table.remoteness,
                )
                expected = np.sort(np.concatenate(shards))
                if table.states.shape[0] != expected.shape[0] or not (
                    table.states == expected
                ).all():
                    raise SolverError(
                        f"checkpointed level {k} does not match the "
                        "discovered frontier — stale checkpoint directory?"
                    )
                owners = owner_shard_np(table.states, S)
                for s in range(S):
                    sel = owners == s
                    pv[s, : sel.sum()] = table.values[sel]
                    pr[s, : sel.sum()] = table.remoteness[sel]
            else:
                window_levels = [
                    k + j
                    for j in range(1, g.max_level_jump + 1)
                    if (k + j) in padded_cache
                ]
                window_caps = tuple(
                    padded_cache[L][0].shape[1] for L in window_levels
                )
                window_flat = []
                for L in window_levels:
                    window_flat.extend(padded_cache[L])
                values, remoteness, misses = self._backward_fn(cap, window_caps)(
                    stacked, *window_flat
                )
                if self.paranoid and int(np.asarray(misses).sum()) > 0:
                    raise SolverError(
                        f"level {k}: consistency failures (missed child "
                        "lookups or zero-move non-primitive positions)"
                    )
                values = np.asarray(values)
                remoteness = np.asarray(remoteness)
                # Global table for this level: concatenate shards (kept
                # sharded on device during the solve; materialized for the
                # result).
                gs, gv, gr = [], [], []
                for s in range(S):
                    n = shards[s].shape[0]
                    gs.append(shards[s])
                    gv.append(values[s, :n])
                    gr.append(remoteness[s, :n])
                    pv[s, :n] = values[s, :n]
                    pr[s, :n] = remoteness[s, :n]
                states = np.concatenate(gs)
                order = np.argsort(states)
                table = LevelTable(
                    states=states[order],
                    values=np.concatenate(gv)[order],
                    remoteness=np.concatenate(gr)[order],
                )
            resolved[k] = table
            padded_cache[k] = (stacked, pv, pr)
            for done in [d for d in padded_cache if d > k + g.max_level_jump]:
                del padded_cache[done]
            if self.logger is not None:
                self.logger.log(
                    {
                        "phase": "backward",
                        "level": k,
                        "n": int(table.states.shape[0]),
                        "shards": S,
                        "resumed": from_checkpoint,
                        "secs": time.perf_counter() - t0,
                    }
                )
            if self.checkpointer is not None and not from_checkpoint:
                self.checkpointer.save_level(k, table)
        return resolved

    # ------------------------------------------------------------------ solve

    def solve(self) -> SolveResult:
        g = self.game
        S = self.S
        t0 = time.perf_counter()
        init, start_level = canonical_scalar(g, g.initial_state())
        if self.checkpointer is not None:
            self.checkpointer.bind_game(g.name)
        global_pools = (
            self.checkpointer.load_frontiers()
            if self.checkpointer is not None
            else None
        )
        if global_pools is not None:
            pools = {
                k: self._repartition(np.asarray(v, dtype=g.state_dtype))
                for k, v in global_pools.items()
            }
        else:
            owner = int(owner_shard_np(np.array([init], np.uint64), S)[0])
            shards = [np.empty(0, g.state_dtype) for _ in range(S)]
            shards[owner] = np.array([init], g.state_dtype)
            pools = {start_level: shards}
            self._forward(pools, start_level)
            if self.checkpointer is not None:
                self.checkpointer.save_frontiers(
                    {
                        k: np.sort(np.concatenate(v))
                        for k, v in pools.items()
                    }
                )
        t_forward = time.perf_counter() - t0
        resolved = self._backward(pools)
        t_total = time.perf_counter() - t0
        root = resolved[start_level]
        i = int(np.searchsorted(root.states, init))
        num_positions = sum(t.states.shape[0] for t in resolved.values())
        stats = {
            "game": g.name,
            "shards": S,
            "positions": num_positions,
            "levels": len(resolved),
            "secs_forward": t_forward,
            "secs_total": t_total,
            "positions_per_sec": num_positions / max(t_total, 1e-9),
        }
        if self.logger is not None:
            self.logger.log({"phase": "done", **stats})
        return SolveResult(
            g, int(root.values[i]), int(root.remoteness[i]), resolved, stats
        )
