"""The sharded level-synchronous solver (multi-device).

This is the TPU rebuild of the reference's distributed runtime proper
(src/process.py's cross-rank behavior, SURVEY.md §3.2-3.3 and §5.8):

  reference (per message/position)      here (per level, per shard)
  ------------------------------------  --------------------------------------
  comm.send(Job(LOOK_UP, child),        forward: expand locally, bucket all
     dest=hash(child) % world_size)     children by owner_shard(child), one
                                        lax.all_to_all over the ICI mesh,
                                        then sort-unique locally (dedup is
                                        local after owner routing)
  per-rank memo dict {pos: value}       per-shard sorted (states, cells)
                                        arrays — the hash-partitioned
                                        position table in sharded HBM
  SEND_BACK child result to parent      backward: owner-routed result
                                        reduction. Default (GAMESMAN_
                                        BACKWARD=edges, uniform-level-jump
                                        games): forward stored each child's
                                        unique-index within its owner's
                                        level slice, so the backward step is
                                        all_to_all the stored edge indices,
                                        gather packed cells on the owner,
                                        all_to_all the reply — no search, no
                                        re-expansion. Fallback (=lookup, or
                                        any level without stored edges):
                                        child-state queries all_to_all to
                                        owner shards, local sort-merge-join/
                                        binary-search lookup, packed
                                        (value,remoteness) cells back
  FINISHED broadcast                    the backward loop reaching the root

Memory scaling: every per-shard buffer — level slice, window slice, routing
buffers — is O(level/S), never O(level). The round-1 design all_gathered the
whole solved window onto every shard (O(level) per shard), which could not
reach the 6x6/6x7 targets; this owner-routed backward is the scalable shape
SURVEY.md §5.8 prescribes (VERDICT.md round 1, item 2).

Device residency: for uniform_level_jump games the frontier chains on device
shard-to-shard across levels (the next frontier IS the routed dedup output,
resized to the next capacity bucket on device); multi-jump games (children
span levels) keep per-level POOLS on device, merged by a per-target-level
sort-unique kernel (_merge_fn) as each level's routed children arrive. The
backward window is the previously-resolved level's device triples (or a
host-spilled stream, see _run_backward_step_streamed). Host work per level
is counts syncs only — no np.union1d, no per-level downloads (VERDICT r1
item 3, r2 item 5).

Capacity planning: all_to_all buffers are [num_shards, capacity] with
SENTINEL padding. Overflow (a shard sending more than capacity to one peer)
is detected via per-destination counts returned from the kernel and retried
with a doubled capacity — the "capacity counters + host-side spill loop
(rare path)" design of SURVEY.md §5.8. `spill_retries` counts the retries
(observable; tests force the path deterministically by shrinking
`_initial_route_cap`).

Like the single-device engine, compiled steps are cached process-wide
(solve/engine._KERNELS via get_kernel) keyed on game identity, mesh devices
and shapes, and capacities are power-of-two buckets — re-instantiated solvers
reuse XLA executables, and the shape count stays O(log max-frontier).

Shard-count invariance (same tables for 1 and N shards) is the test contract
replacing the reference's `mpirun -np 1` vs `-np N` (SURVEY.md §4.2).
"""

from __future__ import annotations

import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from gamesmanmpi_tpu.core.codec import (
    pack_cells,
    pack_cells_np,
    unpack_cells,
    unpack_cells_np,
)
from gamesmanmpi_tpu.core.hashing import owner_shard, owner_shard_np
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame
from gamesmanmpi_tpu.ops.combine import combine_children
from gamesmanmpi_tpu.ops.dedup import (
    compact_method,
    compaction_sort_bytes,
    sort_unique,
)
from gamesmanmpi_tpu.ops.mergesort import backend_key, use_merge_sort
from gamesmanmpi_tpu.ops.lookup import (
    lookup_sorted,
    lookup_window,
    search_method,
)
from gamesmanmpi_tpu.ops.padding import bucket_size
from gamesmanmpi_tpu.ops.provenance import (
    combine_edge_cells,
    dedup_provenance,
    provenance_sort_bytes,
)
from gamesmanmpi_tpu.obs import (
    SolveStatusTracker,
    Span,
    default_registry,
    maybe_status_server,
)
from gamesmanmpi_tpu.obs import flightrec
from gamesmanmpi_tpu.obs import status as obs_status
from gamesmanmpi_tpu.parallel.mesh import AXIS, make_mesh, shard_map
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.resilience import preempt
from gamesmanmpi_tpu.resilience.coordination import (
    ABORT,
    OK,
    RETRY,
    CoordinatedAbort,
    CoordinationError,
    coordination_from_env,
)
from gamesmanmpi_tpu.resilience import memguard
from gamesmanmpi_tpu.resilience.retry import is_transient, retry_call
from gamesmanmpi_tpu.resilience.supervisor import maybe_watchdog
from gamesmanmpi_tpu.store import WriteTicket, default_store
from gamesmanmpi_tpu.utils.checkpoint import (
    TORN_NPZ_ERRORS,
    CheckpointGeometryError,
    reshard_enabled,
    reshard_shard_stream,
)
from gamesmanmpi_tpu.utils.env import (
    env_float as _env_float,
    env_opt,
    env_str,
)
from gamesmanmpi_tpu.ops.fused import (
    fused_dedup_method,
    fused_dedup_provenance,
    fused_enabled,
    fused_sort_unique,
)
from gamesmanmpi_tpu.solve.engine import (
    LevelTable,
    SolveResult,
    SolverError,
    _backward_block,
    _device_store_bytes,
    _env_int,
    canonical_children,
    canonical_scalar,
    get_kernel,
    roofline_stats,
    set_dispatch_sink,
    tally_dispatch,
)


def _window_block() -> int:
    """Max per-shard window-level capacity kept resident in HBM.

    Window levels wider than this are spilled to host after resolving and
    STREAMED back through HBM in blocks during lookup (see
    _run_backward_step_streamed) — per-shard peak window memory becomes
    O(block), not O(level/S). This is the capacity mechanism the 7x6 row of
    docs/ARCHITECTURE.md's plan needs: at that scale one window level is
    ~244 GB/chip on a v4-32, far beyond HBM. Power-of-two positions per
    shard, env GAMESMAN_WINDOW_BLOCK.
    """
    n = _env_int("GAMESMAN_WINDOW_BLOCK", 1 << 22)
    if n <= 0:
        return 1 << 62  # 0 = never spill (mirrors GAMESMAN_BACKWARD_BLOCK)
    return max(256, 1 << (n - 1).bit_length())


def _pad_shards(shard_arrays: List[np.ndarray], cap: int) -> np.ndarray:
    """Stack per-shard 1-D state arrays into [S, cap] with SENTINEL pad.

    The dtype (and sentinel) follows the input arrays' dtype.
    """
    from gamesmanmpi_tpu.core.bitops import sentinel_for

    S = len(shard_arrays)
    dtype = shard_arrays[0].dtype
    out = np.full((S, cap), sentinel_for(dtype), dtype=dtype)
    for s, arr in enumerate(shard_arrays):
        out[s, : arr.shape[0]] = arr
    return out


def _fetch_global(arr) -> np.ndarray:
    """np.asarray that works across processes.

    A P(AXIS)-sharded array under multi-process execution spans
    non-addressable devices, which plain np.asarray refuses; the gather
    collective (multihost_utils.process_allgather) fetches the
    fully-replicated value instead — every rank ends up holding the
    global copy, which is exactly what the callers (level
    materialization, whole-level host spill) need to stay byte-identical
    with the single-process engine.
    """
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr))
    return np.asarray(arr)


def _route_by_owner(flat, S: int, cap_out: int, sentinel):
    """Bucket a flat state array by owner shard for all_to_all routing.

    The device half of the reference's `dest=hash(pos) % world_size` send
    (SURVEY.md §3.2): stable-sort by destination, position each element in
    its destination bucket, scatter into a [S, cap_out] send buffer.

    Returns (send [S, cap_out] sentinel-padded, counts [S] int32 true
    per-destination sizes for overflow detection, s_owner, pos, order) —
    the last three let the caller route replies back to the original layout.
    """
    owner = jnp.where(flat == sentinel, S, owner_shard(flat, S)).astype(
        jnp.int32
    )
    order = jnp.argsort(owner, stable=True)
    s_owner = owner[order]
    s_elems = flat[order]
    first = jnp.searchsorted(s_owner, jnp.arange(S + 1))
    pos = jnp.arange(s_owner.shape[0]) - first[jnp.clip(s_owner, 0, S)]
    counts = (first[1:] - first[:-1]).astype(jnp.int32)
    send = jnp.full((S, cap_out), sentinel, dtype=flat.dtype)
    # Out-of-range rows (owner==S) and overflow (pos>=cap_out) drop.
    send = send.at[s_owner, pos].set(s_elems, mode="drop")
    return send, counts, s_owner, pos, order


def _sharded_forward_step(game: TensorGame, S: int, route_cap: int, local,
                          merge: bool | None = None,
                          compact: str | None = None,
                          provenance: bool = False,
                          fused: str | None = None):
    """Per-shard forward body: expand -> owner-bucket -> all_to_all -> dedup.

    local: [1, cap] this shard's frontier slice (shard_map gives the leading
    mesh axis). Returns ([1, S*route_cap] unique children, then REPLICATED
    control-plane outputs: [S] per-shard unique counts and [S, S] per-
    (src,dst) send counts for overflow detection). Control outputs are
    all_gathered on device so the host can read them under multi-host
    execution too, where a P(AXIS)-sharded array is not fully addressable.

    provenance=True additionally threads the owner's dedup-sort provenance
    back to the parent shard (the sharded half of the edge-cached backward,
    ops/provenance): the dedup runs as dedup_provenance, each routed slot's
    unique-index-within-owner travels back through a second all_to_all, and
    the routing bookkeeping is folded into one [cap*M] `slot` map — slot[j]
    is the linear index into the [S, route_cap] reply buffer where child
    slot j's answer will sit during backward (-1 = no child). Extra outputs
    (before the control plane): eidx [1, S*route_cap] int32, slot
    [1, cap*M] int32.
    """
    sentinel = game.sentinel
    local = local[0]
    valid = local != sentinel
    prim = game.primitive(local)
    active = valid & (prim == UNDECIDED)
    children, _ = canonical_children(game, local, active)
    flat = children.reshape(-1)
    send, counts, s_owner, pos, order = _route_by_owner(
        flat, S, route_cap, sentinel
    )
    routed = jax.lax.all_to_all(send, AXIS, split_axis=0, concat_axis=0,
                                tiled=True)
    # fused (ISSUE 14): the dedup after the route runs through the fused
    # rank/sort+dedup stage (ops/fused) — per-shard callback on CPU,
    # single-pair-sort scatterinv on accelerators. The routed buffer has
    # no dense real prefix (each source row is sentinel-padded), so no
    # count limit applies; the collectives around the dedup are untouched,
    # which is what keeps these dispatch sites inside _retry_collective
    # (GM603) exactly as before.
    if not provenance:
        if fused:
            uniq, count = fused_sort_unique(routed.reshape(-1), None,
                                            fused, merge, compact)
        else:
            uniq, count = sort_unique(routed.reshape(-1), merge, compact)
        all_counts = jax.lax.all_gather(count, AXIS)  # [S] replicated
        all_sends = jax.lax.all_gather(counts, AXIS)  # [S, S] replicated
        return uniq[None], all_counts, all_sends
    if fused:
        uniq, count, uidx = fused_dedup_provenance(
            routed.reshape(-1), None, fused, merge, compact
        )
    else:
        uniq, count, uidx = dedup_provenance(routed.reshape(-1), merge,
                                             compact)
    # Route each child's unique-index-within-owner back to its parent:
    # uidx is in routed layout (row i = slots received from source i), so
    # the return all_to_all lands row o of the parent's eidx with the uids
    # of the children it sent to owner o, in routing order.
    eidx = jax.lax.all_to_all(
        uidx.reshape(S, route_cap), AXIS, split_axis=0, concat_axis=0,
        tiled=True,
    )
    # slot[j]: where child slot j's reply lives in eidx.reshape(-1). Out-of-
    # range rows (sentinel children, owner==S) and overflow (pos >=
    # route_cap — the host retries it at a larger capacity) map to -1.
    in_range = (s_owner < S) & (pos < route_cap)
    lin = jnp.where(in_range, s_owner * route_cap + pos, -1).astype(jnp.int32)
    slot = jnp.full((flat.shape[0],), -1, jnp.int32).at[order].set(lin)
    all_counts = jax.lax.all_gather(count, AXIS)  # [S] replicated
    all_sends = jax.lax.all_gather(counts, AXIS)  # [S, S] replicated
    return uniq[None], eidx.reshape(-1)[None], slot[None], all_counts, \
        all_sends


def _route_core(game: TensorGame, S: int, qcap: int, local):
    """Shared backward prologue: expand + owner-route the child queries.

    local: [cap] (already unwrapped). Returns (queries [S, qcap], qcounts
    [S], s_owner, pos, order) — the bookkeeping un-permutes replies back to
    the [B, M] child layout in _reply_core. Used by both the fused backward
    step and the streamed route phase so the two can never drift.
    """
    sentinel = game.sentinel
    prim = game.primitive(local)
    undecided = (local != sentinel) & (prim == UNDECIDED)
    children, _ = canonical_children(game, local, undecided)
    flat = children.reshape(-1)
    send, qcounts, s_owner, pos, order = _route_by_owner(
        flat, S, qcap, sentinel
    )
    queries = jax.lax.all_to_all(send, AXIS, split_axis=0, concat_axis=0,
                                 tiled=True)
    return queries, qcounts, s_owner, pos, order


def _reply_core(game: TensorGame, S: int, qcap: int, local, reply, s_owner,
                pos, order):
    """Shared backward epilogue: un-permute reply cells + negamax combine.

    reply: [S, qcap] packed cells AFTER the return all_to_all (a hit always
    carries a decided value — WIN/LOSE/TIE != UNDECIDED=0 — so the
    UNDECIDED cell doubles as the miss flag). Children are re-expanded for
    the mask (cheap elementwise). Returns (values [cap], remoteness [cap],
    misses scalar, NOT yet psum'd).
    """
    sentinel = game.sentinel
    valid = local != sentinel
    prim = game.primitive(local)
    undecided = valid & (prim == UNDECIDED)
    children, mask = canonical_children(game, local, undecided)
    B, M = children.shape
    if qcap == 0:
        child_vals = jnp.full((B, M), UNDECIDED, dtype=jnp.uint8)
        child_rem = jnp.zeros((B, M), dtype=jnp.int32)
        hit = jnp.zeros((B, M), dtype=bool)
    else:
        in_range = (s_owner < S) & (pos < qcap)
        got = reply[jnp.clip(s_owner, 0, S - 1), jnp.clip(pos, 0, qcap - 1)]
        got = jnp.where(in_range, got, 0)
        flat_reply = (
            jnp.zeros((B * M,), dtype=reply.dtype).at[order].set(got)
        )
        child_vals, child_rem = unpack_cells(flat_reply.reshape(B, M))
        hit = child_vals != UNDECIDED
    values, remoteness = combine_children(child_vals, child_rem, mask)
    values = jnp.where(undecided, values, jnp.where(valid, prim, UNDECIDED))
    remoteness = jnp.where(undecided, remoteness, 0)
    # Consistency counters (SURVEY.md §5.2): missed child lookups (including
    # routing overflow, which the host retries) + zero-move UNDECIDED
    # positions (see engine.resolve_level).
    misses = jnp.sum(mask & ~hit) + jnp.sum(
        undecided & ~jnp.any(mask, axis=-1)
    )
    return values, remoteness, misses


def _sharded_backward_step(game: TensorGame, S: int, qcap: int, local,
                           window_flat, method: str | None = None):
    """Per-shard backward body: owner-routed child-value reduction.

    The SEND_BACK/RESOLVE analog (SURVEY.md §3.3, §5.8): child queries are
    all_to_all'd to their owner shards, answered by local binary search in
    the owner's sorted window slices, and the (value, remoteness) replies —
    packed into one uint32 cell each (core/codec) — are all_to_all'd back
    and un-permuted to the [B, M] child layout for the negamax combine.

    local: [1, cap] this shard's level slice. window_flat: flat sequence of
    (states, values, remoteness) triples, one per window level, each the
    LOCAL [1, capL] shard slice (NOT gathered — per-shard memory is
    O(level/S)). qcap == 0 means no window (deepest level; no queries).

    Returns ([1, cap] values, [1, cap] remoteness, [1] misses,
    [1, S] per-destination query counts for overflow detection).
    """
    local = local[0]
    if qcap == 0:
        reply = s_owner = pos = order = None
        qcounts = jnp.zeros((S,), dtype=jnp.int32)
    else:
        window = tuple(
            (window_flat[i][0], window_flat[i + 1][0], window_flat[i + 2][0])
            for i in range(0, len(window_flat), 3)
        )
        queries, qcounts, s_owner, pos, order = _route_core(
            game, S, qcap, local
        )
        vals, rems, _ = lookup_window(queries.reshape(-1), window, method)
        reply = pack_cells(vals, rems).reshape(S, qcap)
        reply = jax.lax.all_to_all(reply, AXIS, split_axis=0, concat_axis=0,
                                   tiled=True)
    values, remoteness, misses = _reply_core(
        game, S, qcap, local, reply, s_owner, pos, order
    )
    # Control plane replicated for multi-host readability (see forward step).
    total_misses = jax.lax.psum(misses, AXIS)
    all_qcounts = jax.lax.all_gather(qcounts, AXIS)  # [S, S] replicated
    return values[None], remoteness[None], total_misses, all_qcounts


def _sharded_route_step(game: TensorGame, S: int, qcap: int, local):
    """Streamed backward, phase 1: expand + owner-route the child queries.

    Splits _sharded_backward_step at the window boundary (same _route_core)
    so the window can be streamed through HBM between phases instead of
    being resident. The routing bookkeeping (s_owner, pos, order — how to
    un-permute replies back to the [B, M] child layout) leaves the kernel
    as P(AXIS) outputs and is fed back to _sharded_reply_step unchanged.
    """
    local = local[0]
    queries, qcounts, s_owner, pos, order = _route_core(game, S, qcap, local)
    all_qcounts = jax.lax.all_gather(qcounts, AXIS)  # [S, S] replicated
    # The accumulator is born on device here (one extra output) — creating
    # it outside would cost a dedicated zeros kernel compile per shape.
    acc = jnp.zeros(queries.shape, dtype=jnp.uint32)
    return (
        queries[None],
        acc[None],
        s_owner.astype(jnp.int32)[None],
        pos.astype(jnp.int32)[None],
        order.astype(jnp.int32)[None],
        all_qcounts,
    )


def _sharded_lookup_acc_step(queries, acc, wstates, wvals, wrem,
                             method: str | None = None):
    """Streamed backward, phase 2 (once per window block): local lookup.

    Looks this shard's routed queries up in ONE block of its window slice
    and accumulates hits into the packed-cell buffer. Blocks partition a
    sorted level slice, so each query hits in at most one block across the
    whole stream; a hit cell is nonzero (decided value), so accumulate is a
    select. No collectives — pure local compute.
    """
    q = queries[0].reshape(-1)
    v, r, h = lookup_sorted(q, wstates[0], wvals[0], wrem[0], method)
    cell = pack_cells(v, r)
    out = jnp.where(h, cell, acc[0].reshape(-1))
    return out.reshape(acc[0].shape)[None]


def _sharded_reply_step(game: TensorGame, S: int, qcap: int, local, acc,
                        s_owner, pos, order):
    """Streamed backward, phase 3: reply all_to_all + negamax combine.

    The tail of _sharded_backward_step (same _reply_core): accumulated
    cells travel back to the querying shards, are un-permuted into the
    [B, M] child layout, and combined.
    """
    local = local[0]
    reply = jax.lax.all_to_all(acc[0], AXIS, split_axis=0, concat_axis=0,
                               tiled=True)
    values, remoteness, misses = _reply_core(
        game, S, qcap, local, reply, s_owner[0], pos[0], order[0]
    )
    total_misses = jax.lax.psum(misses, AXIS)
    return values[None], remoteness[None], total_misses


def _sharded_edges_route_step(S: int, ecap: int, eidx):
    """Edge-cached backward, phase 1: all_to_all the stored edge indices.

    eidx: [1, S*ecap] this shard's stored edge map (row o = the unique-
    indices, within owner o's deeper-level slice, of the children this
    shard routed to o during forward). After the collective each OWNER
    holds the index requests addressed to it. Also births the packed-cell
    accumulator (one extra output — same rationale as _sharded_route_step).
    """
    e = eidx[0].reshape(S, ecap)
    q = jax.lax.all_to_all(e, AXIS, split_axis=0, concat_axis=0, tiled=True)
    acc = jnp.zeros((S * ecap,), dtype=jnp.uint32)
    return q.reshape(-1)[None], acc[None]


def _sharded_edges_gather_step(q, acc, wvals, wrem, off):
    """Edge-cached backward, phase 2 (once per window block): owner gather.

    Accumulates packed (value, remoteness) cells for the edge requests
    whose index lands in this block [off, off+W) of the owner's deeper-
    level slice. Indices were derived from the very dedup sort that built
    that slice, so every real edge hits in exactly one block; a real cell
    is nonzero (decided value), so accumulation is a select. Pure local
    compute — no collectives, no search.
    """
    qq = q[0]
    W = wvals[0].shape[0]
    rel = qq - off[0]
    hit = (qq >= 0) & (rel >= 0) & (rel < W)
    cells = pack_cells(wvals[0], wrem[0])
    got = cells[jnp.clip(rel, 0, W - 1)]
    return jnp.where(hit, got, acc[0])[None]


def _sharded_edges_reply_step(game: TensorGame, S: int, ecap: int, local,
                              acc, slot):
    """Edge-cached backward, phase 3: reply all_to_all + negamax combine.

    The accumulated cells travel back to the querying shards; the stored
    `slot` map places each child's cell directly into the [B, M] child
    layout — no un-permute sort, no re-expansion (primitive() is the only
    per-state work). Misses are structurally impossible for real edges;
    the consistency counter tracks only zero-move non-primitive rows.
    """
    local = local[0]
    reply = jax.lax.all_to_all(
        acc[0].reshape(S, ecap), AXIS, split_axis=0, concat_axis=0,
        tiled=True,
    ).reshape(-1)
    sl = slot[0]
    got = jnp.where(
        sl >= 0, reply[jnp.clip(sl, 0, reply.shape[0] - 1)], jnp.uint32(0)
    )
    cv, cr, mask = combine_edge_cells(got, game.max_moves)
    valid = local != game.sentinel
    prim = game.primitive(local)
    undecided = valid & (prim == UNDECIDED)
    mask = mask & undecided[:, None]
    values, remoteness = combine_children(cv, cr, mask)
    values = jnp.where(undecided, values, jnp.where(valid, prim, UNDECIDED))
    remoteness = jnp.where(undecided, remoteness, 0)
    misses = jnp.sum(undecided & ~jnp.any(mask, axis=-1))
    return values[None], remoteness[None], jax.lax.psum(misses, AXIS)


class _HostSpill:
    """A resolved level spilled to host, multi-host safe.

    Holds each ADDRESSABLE shard's rows as numpy (downloaded via
    `addressable_shards`, so each process touches only its own devices —
    a plain np.asarray on a P(AXIS)-sharded array raises under multi-host
    execution) and re-uploads column blocks as global arrays via
    jax.make_array_from_single_device_arrays.
    """

    def __init__(self, global_shape, sharding, shards):
        self.global_shape = global_shape  # (S, cap)
        self.sharding = sharding
        #: list of (device, index-tuple, np rows [1, cap]) per local shard
        self.shards = shards

    @classmethod
    def download(cls, arr) -> "_HostSpill":
        shards = [
            (s.device, s.index, np.asarray(s.data))
            for s in arr.addressable_shards
        ]
        return cls(arr.shape, arr.sharding, shards)

    @property
    def cap(self) -> int:
        return self.global_shape[1]

    def block(self, off: int, width: int):
        """Upload rows [:, off:off+width] as a global [S, width] array."""
        parts = [
            jax.device_put(rows[:, off:off + width], device)
            for device, _, rows in self.shards
        ]
        return jax.make_array_from_single_device_arrays(
            (self.global_shape[0], width), self.sharding, parts
        )


class _SLevel:
    """One discovered level, sharded: per-shard counts + device/host states.

    eidx/slot/ecap are the forward pass's edge provenance (see
    _sharded_forward_step provenance=True): this level's out-edge indices
    into the NEXT level's per-owner prefixes, plus the slot map that places
    reply cells back into the [B, M] child layout. Each is a jax
    P(AXIS)-sharded array, a _HostSpill (budget-evicted), or None (no edges
    — lookup backward for this level).
    """

    __slots__ = ("counts", "dev", "host", "eidx", "slot", "ecap")

    def __init__(self, counts: np.ndarray, dev, host):
        self.counts = counts  # np [S] real (non-sentinel) per-shard counts
        self.dev = dev  # jax [S, cap] P(AXIS)-sharded, sorted slices, or None
        self.host = host  # list of per-shard sorted np arrays, or None
        self.eidx = None  # [S, S*ecap] int32 edge indices (see class doc)
        self.slot = None  # [S, cap*M] int32 reply-slot map
        self.ecap = 0  # per-(src,dst) routing capacity the edges used

    def host_shards(self) -> List[np.ndarray]:
        if self.host is None:
            stacked = _fetch_global(self.dev)
            self.host = [
                stacked[s, : int(self.counts[s])]
                for s in range(stacked.shape[0])
            ]
        return self.host


class ShardedSolver:
    """Hash-partitioned solver over a 1-D device mesh."""

    def __init__(
        self,
        game: TensorGame,
        *,
        num_shards: int | None = None,
        mesh=None,
        min_bucket: int = 256,
        paranoid: bool = False,
        logger=None,
        checkpointer=None,
        force_generic: bool = False,
        store_tables: bool = True,
    ):
        self.game = game
        self.store_tables = store_tables
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        self.S = self.mesh.devices.shape[0]
        self.min_bucket = min_bucket
        self.paranoid = paranoid
        self.logger = logger
        self.checkpointer = checkpointer
        self.fast = bool(game.uniform_level_jump) and not force_generic
        self.device_store_bytes = _device_store_bytes()
        self.backward_block = _backward_block()
        # The async block store (ISSUE 11): shared with the checkpointer
        # (one byte budget, one write-behind queue, one prefetch pool per
        # process). Wrapped/stubbed checkpointers in tests may not expose
        # a store — fall back to the process default.
        self.store = (
            getattr(checkpointer, "store", None) if checkpointer is not None
            else None
        ) or default_store()
        #: store counters at solve start — stats() reports this solve's
        #: deltas (the store is process-wide and outlives solves).
        self._store_t0 = self.store.stats()
        #: pipelined checkpoint seals (single-process write-behind): each
        #: entry is (tickets, seal_fn); the oldest flushes when the queue
        #: exceeds one level's worth, and everything flushes at phase
        #: boundaries — so level k's DEFLATE+fsync overlaps level k-1's
        #: compute while the payload-before-seal order stays absolute.
        self._pending_seals: List = []
        #: edge arrays dropped to the disk tier (sealed edge-shard files)
        #: because the host-RAM spill budget was exhausted — reloaded via
        #: the store (prefetch makes them cache hits) during backward.
        self.edges_bytes_disk = 0
        #: host-RAM bytes currently held by budget-evicted edge spills,
        #: capped by the store cache budget (the host tier).
        self._host_spill_bytes = 0
        # Route-capacity headroom (strict parse, fail-fast like the other
        # capacity knobs): see _initial_route_cap.
        raw = env_opt("GAMESMAN_ROUTE_HEADROOM")
        try:
            self.route_headroom = float(raw) if raw else 2.0
        except ValueError:
            raise SolverError(
                f"GAMESMAN_ROUTE_HEADROOM={raw!r} is not a number"
            ) from None
        import math

        if not math.isfinite(self.route_headroom) or self.route_headroom <= 0:
            # nan/inf parse as floats but would crash mid-solve inside
            # _initial_route_cap's int() — fail here, at construction.
            raise SolverError(
                f"GAMESMAN_ROUTE_HEADROOM must be a finite number > 0, "
                f"got {self.route_headroom}"
            )
        # Backward strategy (ISSUE 3): 'edges' = edge-cached provenance
        # backward (gathers + collectives, no search, no re-expansion) for
        # every level whose edges exist, falling back to the lookup join
        # per level where they don't (pre-edge checkpoints, generic-path
        # games, budget-evicted big runs resumed without edge files);
        # 'lookup' = always the owner-routed sort-merge/binary-search join.
        # Strict parse, fail-fast at construction like the other knobs.
        raw = env_str("GAMESMAN_BACKWARD", "edges")
        if raw not in ("edges", "lookup"):
            raise SolverError(
                f"GAMESMAN_BACKWARD={raw!r}: expected 'edges' or 'lookup'"
            )
        self.backward_mode = raw
        # Edge provenance rides the uniform-level-jump fast path only:
        # the generic path's per-target-level pool merges re-sort each
        # pool as later contributions arrive, which would invalidate any
        # index issued before the merge.
        self.use_edges = self.backward_mode == "edges" and self.fast
        #: levels resolved via the edge-cached backward (the observable
        #: for the A/B and fallback tests).
        self.backward_edges_levels = 0
        # Background compiles of the edge-backward shapes (same policy as
        # the single-device engine: only worth it where compiles are
        # remote ~15 s RPCs; on CPU they would just slow the suite).
        flag = env_str("GAMESMAN_PRECOMPILE", "auto")
        if flag == "auto":
            self.precompile = jax.default_backend() != "cpu"
        else:
            self.precompile = flag not in ("0", "off", "false")
        #: bytes of edge arrays evicted from device to host (big-run mode).
        self.edges_bytes_spilled = 0
        #: checkpoint/spill tier I/O accounting: raw array bytes handed to
        #: the checkpointer vs bytes that actually landed on disk (the
        #: delta is what GAMESMAN_CKPT_COMPRESS — incl. the block-framed
        #: ``blocks`` mode — saved this run; see stats()["ckpt_bytes_*"]).
        self.ckpt_bytes_raw = 0
        self.ckpt_bytes_stored = 0
        #: number of capacity-overflow retries taken (forward + backward);
        #: the observable for the spill-path tests.
        self.spill_retries = 0
        #: per-shard window capacity above which resolved levels spill to
        #: host and stream back through HBM in blocks during lookup.
        self.window_block = _window_block()
        #: number of window blocks streamed through HBM (observable for the
        #: window-streaming tests; 0 when every window stayed resident).
        self.window_stream_blocks = 0
        #: hybrid seam: materialize the backward root level's global table
        #: even in big-run mode (the boundary join reads it); plain solves
        #: leave it False and take the device-replicated root answer only.
        self.materialize_root_table = False
        # Analytic traffic counters (SURVEY.md §5.5): payload bytes of the
        # all_to_all collectives and operand bytes of the sort/gather
        # kernels — the denominators that make positions/sec readable
        # against ICI/HBM rooflines (docs/ARCHITECTURE.md "Efficiency
        # accounting").
        self.bytes_routed = 0
        self.bytes_sorted = 0
        self.bytes_gathered = 0
        #: transient level-step failures absorbed by retry (stats field).
        self.retries = 0
        #: ISSUE 14 dispatch accounting (see engine.note_dispatch): device
        #: computations/transfers this solve issued, with the per-(phase,
        #: level) breakdown the fused A/B asserts on.
        self.dispatch_total = 0
        self.level_dispatches: Dict[tuple, int] = {}
        self.dispatch_by_kind: Dict[str, int] = {}
        #: elastic resume (ISSUE 13): shard count the adopted checkpoint
        #: tree was sealed at when it differs from this run's (None = no
        #: reshard happened), and how many levels fell back from the
        #: edge-cached backward because their sealed edge shards carry a
        #: foreign geometry (edge slot maps cannot re-map — the per-level
        #: lookup join is the structural fallback).
        self.resharded_from = None
        self.edges_geometry_fallback_levels = 0
        #: this process's rank in the multi-process run (0 single-process).
        self.rank = jax.process_index()
        self.num_processes = jax.process_count()
        # Cross-rank retry/abort consensus (resilience/coordination.py):
        # built from GAMESMAN_COORD_ADDR under multi-process execution so
        # transient faults at collective fault points are retried by ALL
        # ranks together or aborted by all ranks together — a lone rank
        # re-entering a step that contains an all_to_all while its peers
        # proceed would wedge the job forever. None = rank-local retry
        # (single process, or coordination unconfigured).
        self.coord = coordination_from_env(self.rank, self.num_processes)
        #: per-collective deadline (GAMESMAN_COLLECTIVE_TIMEOUT, seconds):
        #: under multi-process execution a peer's death leaves this rank
        #: BLOCKED inside the collective — uninterruptible from Python —
        #: so the only honest recovery is the watchdog contract: dump
        #: per-rank progress and exit 124 with the checkpoint prefix
        #: intact. 0 = off.
        self.collective_timeout = _env_float(
            "GAMESMAN_COLLECTIVE_TIMEOUT", 0.0
        )
        #: phase/level progress for the watchdog (replaced atomically,
        #: never mutated — same contract as the single-device engine's).
        self.progress: dict = {"phase": "init", "rank": self.rank}
        #: live-status progress model + endpoint (obs/status.py,
        #: GAMESMAN_STATUS_PORT): rank 0 additionally serves the
        #: fleet-merged view scraped via the coordinator address book.
        self.status_tracker = SolveStatusTracker()
        self._status_server = None
        # Mesh identity participates in the process-wide kernel cache key
        # (same shard count over different device sets must not share).
        self._mesh_key = tuple(d.id for d in self.mesh.devices.flat)
        self._sharding = NamedSharding(self.mesh, P(AXIS))

    def _check_preempt(self, phase: str, level) -> None:
        """Rank-coordinated level-boundary preemption point (ISSUE 12).

        Single-process: one flag check. Multi-process: every rank folds
        its local grace flag into an epoch round at this boundary — the
        signal lands asynchronously, so without consensus rank A could
        unwind at level k while rank B enters level k's first collective
        and wedges until the collective deadline. The round (ABORT from
        any preempted rank beats OK) makes every rank raise
        :class:`PreemptionRequested` at the SAME program point, so the
        whole world drains to exit 75 together with the deepest mutually
        sealed prefix on disk. A CoordinationError here converts to
        CoordinatedAbort via _propose_step — exit 124, still resumable.
        """
        # Host-memory guard first (ISSUE 13): past the limit this rank
        # raises HostMemoryExceeded — a clean, classifiable, resumable
        # death at the boundary instead of a kernel OOM-kill mid-level
        # (rank-local by design: peers unwind via the collective
        # deadline, and the campaign's oom policy escalates geometry).
        memguard.check(phase, level=level, logger=self.logger)
        flagged = preempt.requested()
        if self.coord is not None:
            decision = self._propose_step(
                "preempt", level, 0, phase, ABORT if flagged else OK, None
            )
            flagged = flagged or decision != OK
        if flagged:
            preempt.check(phase, level=level, logger=self.logger)
            # A peer was preempted but this rank's own flag is unset
            # (its signal is still in flight): same unwind, attributed.
            raise preempt.PreemptionRequested(
                f"peer rank preempted at {phase} boundary (level {level})"
            )

    def _on_dispatch(self, kind: str) -> None:
        """Dispatch sink (engine.set_dispatch_sink): one shared tally body
        with the single-device engine (engine.tally_dispatch) so the
        gamesman_dispatches_total series can never fork between them."""
        tally_dispatch(self, kind)

    def _retry(self, point: str, fn, reset=None, level=None, entry=None):
        """Level-step retry wrapper (see resilience.retry): the sharded
        steps' inputs — frontier, window triples, edge arrays — stay
        referenced across the step, so re-dispatch is idempotent.

        ``entry`` is the step's host-side prelude — the call site's
        literal ``faults.fire`` — evaluated BEFORE any collective
        dispatches: under multi-process execution that is the one
        program point where a rank-local failure is still safely
        retryable, because no rank has entered the collective yet. With
        a coordination handle the whole retry decision is a cross-rank
        consensus round (_retry_collective); without one (single
        process) the behavior is exactly PR 4's rank-local retry_call.
        """
        if self.coord is None:

            def unit():
                if entry is not None:
                    entry()
                return fn()

            def on_retry(attempt, exc):
                self.retries += 1

            return retry_call(unit, point=point, reset=reset, level=level,
                              logger=self.logger, on_retry=on_retry)
        return self._retry_collective(point, fn, reset, level, entry)

    def _retry_collective(self, point: str, fn, reset, level, entry=None):
        """Collective-safe retry: all ranks enter, retry, or abort a
        level step TOGETHER.

        Protocol per attempt: every rank evaluates the step's entry
        (fault points fire here, before any collective dispatches),
        proposes ok/retry/abort for the shared epoch
        ``<seq>:<point>:L<level>:a<attempt>:pre``, and acts on the
        fleet's decision — so a transient injected on ONE rank turns
        into a retry on EVERY rank (each counts it: the
        ``gamesman_retries_total`` criterion), and a fatal anywhere
        aborts everywhere. A failure DURING the dispatched step (a
        collective transport error) goes through a ``post`` round
        instead: peers that already completed the step will never join
        it, so the round resolves by deadline into a coordinated abort
        — the one correct answer once ranks have diverged — while a
        symmetric failure (all ranks raised) agrees to retry.
        Consensus-service failures (coordinator death) convert to
        CoordinatedAbort, never a hang.
        """
        attempts = max(1, _env_int("GAMESMAN_RETRY_ATTEMPTS", 3))
        base = _env_float("GAMESMAN_RETRY_BASE_SECS", 0.25)
        for attempt in range(1, attempts + 1):
            err = None
            try:
                faults.fire("sharded.collective", step=point, level=level)
                if entry is not None:
                    entry()
            except Exception as e:  # noqa: BLE001 - classified below
                err = e
            verdict = self._verdict_for(err, attempt, attempts)
            decision = self._propose_step(point, level, attempt, "pre",
                                          verdict, err)
            if decision == RETRY:
                self._note_coordinated_retry(point, level, attempt, err)
                if base > 0:
                    time.sleep(base * (2 ** (attempt - 1)))
                if reset is not None:
                    reset()
                continue
            if decision != OK:
                self._coordinated_abort(point, level, err, verdict)
            try:
                with self._collective_deadline(point, level):
                    return fn()
            except Exception as e:  # noqa: BLE001 - classified below
                if not is_transient(e) or attempt >= attempts:
                    raise
                decision = self._propose_step(point, level, attempt,
                                              "post", RETRY, e)
                if decision == RETRY:
                    self._note_coordinated_retry(point, level, attempt, e)
                    if base > 0:
                        time.sleep(base * (2 ** (attempt - 1)))
                    if reset is not None:
                        reset()
                    continue
                self._coordinated_abort(point, level, e, RETRY)
        raise SolverError(
            f"retry loop for {point} level {level} exhausted without a "
            "decision"
        )  # pragma: no cover - every branch returns, continues, or raises

    @staticmethod
    def _verdict_for(err, attempt: int, attempts: int) -> str:
        if err is None:
            return OK
        if is_transient(err) and attempt < attempts:
            return RETRY
        return ABORT

    def _propose_step(self, point: str, level, attempt: int, phase: str,
                      verdict: str, err) -> str:
        tag = f"{point}:L{level}:a{attempt}:{phase}"
        try:
            return self.coord.propose(tag, verdict)
        except CoordinationError as e:
            # The consensus service itself failed (coordinator death,
            # wire junk): abort — a guess here could strand a peer
            # inside a collective.
            raise CoordinatedAbort(
                f"coordination failed at {tag} (rank {self.rank}): {e}"
            ) from (err or e)

    def _note_coordinated_retry(self, point: str, level, attempt: int,
                                err) -> None:
        """Every rank records the fleet-wide retry decision — the
        counters must AGREE across ranks, whichever rank hosted the
        fault (rank-labelled via the registry's constant labels)."""
        self.retries += 1
        default_registry().counter(
            "gamesman_retries_total",
            "transient step failures absorbed by retry",
            point=point,
        ).inc()
        flightrec.record(
            "retry", point=point, attempt=attempt, level=level,
            coordinated=True,
            error=str(err)[:120] if err is not None else "peer",
        )
        if self.logger is not None:
            rec = {
                "phase": "retry",
                "point": point,
                "attempt": attempt,
                "rank": self.rank,
                "coordinated": True,
                "error": str(err)[:200] if err is not None else "peer",
            }
            if level is not None:
                rec["level"] = int(level)
            self.logger.log(rec)

    def _coordinated_abort(self, point: str, level, err, verdict):
        """ABORT decision: raise this rank's own error only when IT was
        the abort cause (verdict ABORT — fail fast with the real fatal).
        A rank whose local failure was retryable (or absent) aborts
        because of a PEER: that must surface as CoordinatedAbort — the
        exception the CLI maps to the exit-124 resumable-abort contract
        — not as a transient traceback that misattributes the abort to
        a fault the fleet would have retried."""
        if err is not None and verdict == ABORT:
            raise err
        detail = (f"rank {self.rank} was healthy" if err is None
                  else f"rank {self.rank} proposed retry for: "
                  f"{str(err)[:200]}")
        raise CoordinatedAbort(
            f"fleet aborted at {point} level {level} ({detail})"
        ) from err

    def _collective_deadline(self, point: str, level):
        """Deadline guard around one dispatched collective step: when a
        peer dies mid-collective this rank blocks forever inside the
        runtime, so a daemon timer dumps this rank's progress and exits
        124 — the watchdog's abort contract, checkpoint prefix intact,
        and every surviving rank does the same within the deadline
        (the 'coordinated resumable abort'). Off unless
        GAMESMAN_COLLECTIVE_TIMEOUT > 0 and the run is multi-process.
        """
        import contextlib

        secs = self.collective_timeout
        if secs <= 0 or self.num_processes <= 1:
            return contextlib.nullcontext()

        @contextlib.contextmanager
        def guard():
            import threading

            def expire():
                from gamesmanmpi_tpu.resilience.supervisor import (
                    WATCHDOG_EXIT_CODE,
                )
                import os
                import sys

                rec = {
                    "phase": "collective_abort",
                    "point": point,
                    "level": level,
                    "rank": self.rank,
                    "deadline_secs": secs,
                    "progress": dict(self.progress),
                }
                sys.stderr.write(
                    f"[coordination] collective deadline expired: {rec}\n"
                )
                sys.stderr.flush()
                default_registry().counter(
                    "gamesman_collective_deadline_expired_total",
                    "collectives aborted by the per-collective deadline",
                    point=point,
                ).inc()
                if self.logger is not None:
                    try:
                        self.logger.log(rec)
                    except Exception:  # noqa: BLE001 - exiting anyway
                        pass
                # Post-mortem before the hard exit: this rank's ring
                # names the collective it died inside (timer thread,
                # never a signal handler — flightrec's locking is fine).
                flightrec.record("collective_deadline", point=point,
                                 level=level)
                flightrec.dump("collective_deadline")
                os._exit(WATCHDOG_EXIT_CODE)

            timer = threading.Timer(secs, expire)
            timer.daemon = True
            timer.start()
            try:
                yield
            finally:
                timer.cancel()

        return guard()

    # ------------------------------------------------------------- jit builds

    def _forward_fn(self, cap: int, route_cap: int,
                    provenance: bool = False):
        """Compiled forward step: [S, cap] states -> routed unique children.

        provenance=True is the edge-cached variant (two extra P(AXIS)
        outputs: eidx + slot, see _sharded_forward_step) — a separate
        program and cache kind, so GAMESMAN_BACKWARD=lookup never pays the
        provenance pair sorts.
        """
        mesh, S = self.mesh, self.S

        # Fused-dedup lowering, resolved at cache-key time (ISSUE 14): the
        # flag changes the traced program, so it rides the lowering tuple —
        # a mid-process GAMESMAN_FUSED flip can neither reuse a kernel
        # traced the other way nor disagree with its key.
        fz = fused_dedup_method() if fused_enabled() else None

        def build(game):
            # resolved at cache-key time
            mb, cm = use_merge_sort(), compact_method()

            def per_shard(local):
                return _sharded_forward_step(game, S, route_cap, local, mb,
                                             cm, provenance, fz)

            data_specs = (P(AXIS), P(AXIS), P(AXIS)) if provenance \
                else (P(AXIS),)
            return shard_map(
                per_shard,
                mesh=mesh,
                in_specs=P(AXIS),
                out_specs=data_specs + (P(), P()),
                check_vma=False,  # all_gathered control outputs ARE replicated
            )

        return get_kernel(
            self.game, "sfwdp" if provenance else "sfwd",
            (self._mesh_key, cap, route_cap), build,
            lowering=(backend_key(), compact_method(), fz or "off"),
        )

    # Edge-backward kernel builders are factored out of their get_kernel
    # call sites so _schedule_backward_edges can queue background compiles
    # under the SAME cache keys the resolve will fetch (see get_kernel /
    # schedule_kernel in solve/engine.py).

    def _eroute_build(self, ecap: int):
        mesh, S = self.mesh, self.S

        def build(game):
            def per_shard(eidx):
                return _sharded_edges_route_step(S, ecap, eidx)

            return shard_map(
                per_shard,
                mesh=mesh,
                in_specs=P(AXIS),
                out_specs=(P(AXIS), P(AXIS)),
            )

        return build

    def _eroute_fn(self, ecap: int):
        """Compiled edge-backward phase 1 (see _sharded_edges_route_step)."""
        return get_kernel(
            self.game, "sert", (self._mesh_key, ecap),
            self._eroute_build(ecap),
        )

    def _egather_build(self, ecap: int, wcap: int):
        mesh = self.mesh

        def build(game):
            return shard_map(
                _sharded_edges_gather_step,
                mesh=mesh,
                in_specs=(P(AXIS),) * 4 + (P(),),
                out_specs=P(AXIS),
            )

        return build

    def _egather_fn(self, ecap: int, wcap: int):
        """Compiled edge-backward phase 2 (one window block's gather)."""
        return get_kernel(
            self.game, "serg", (self._mesh_key, ecap, wcap),
            self._egather_build(ecap, wcap),
        )

    def _ereply_build(self, cap: int, ecap: int):
        mesh, S = self.mesh, self.S

        def build(game):
            def per_shard(local, acc, slot):
                return _sharded_edges_reply_step(game, S, ecap, local, acc,
                                                 slot)

            return shard_map(
                per_shard,
                mesh=mesh,
                in_specs=(P(AXIS),) * 3,
                out_specs=(P(AXIS), P(AXIS), P()),
                check_vma=False,  # psum misses ARE replicated
            )

        return build

    def _ereply_fn(self, cap: int, ecap: int):
        """Compiled edge-backward phase 3 (see _sharded_edges_reply_step)."""
        return get_kernel(
            self.game, "serp", (self._mesh_key, cap, ecap),
            self._ereply_build(cap, ecap),
        )

    def _schedule_backward_edges(self, levels, completed) -> None:
        """Queue background compiles for the edge-backward kernels.

        Every shape is known exactly the moment forward ends — (cap, ecap)
        per level plus the window capacity of the level below — and on the
        relay each program is a ~15 s remote compile, so deepest-first
        scheduling overlaps shallow levels' compilation with deep levels'
        execution: the same plan the single-device engine runs for its
        backward shapes (solve/precompile.py). The avals carry the mesh
        shardings the resolve will call with — AOT executables are strict
        about them (see precompile.sds).
        """
        from gamesmanmpi_tpu.solve.engine import schedule_kernel
        from gamesmanmpi_tpu.solve.precompile import sds

        S = self.S
        shard = self._sharding
        repl = NamedSharding(self.mesh, P())
        dt = self.game.state_dtype
        M = self.game.max_moves
        caps = {
            k: (rec.dev.shape[1] if rec.dev is not None
                else bucket_size(
                    int(rec.counts.max()) if rec.counts.size else 0,
                    self.min_bucket))
            for k, rec in levels.items()
        }
        for k in sorted(levels, reverse=True):
            rec = levels[k]
            if k in completed or (k + 1) not in levels:
                continue
            cap = caps[k]
            ecap = rec.ecap
            if rec.eidx is None:
                # Resume path: edges live only in the checkpoint's sealed
                # npz files (_load_edges reads them level by level during
                # the resolve) — the very scenario where overlapping the
                # ~15 s-per-program compiles matters most. The manifest
                # carries the geometry; schedule only what _load_edges
                # will actually accept (same shards/slot_len validation).
                info = (self.checkpointer.edge_level_info(k)
                        if self.checkpointer is not None else None)
                if (not info or info.get("shards") != S
                        or info.get("slot_len") != cap * M):
                    continue
                ecap = int(info["ecap"])
            # The gather runs against the resident window (cap of k+1 when
            # it fits window_block) or window_block-wide streamed slices —
            # min() covers both, matching _resolve_edges_level's shapes.
            wcap = min(caps[k + 1], self.window_block)
            schedule_kernel(
                self.game, "sert", (self._mesh_key, ecap),
                self._eroute_build(ecap),
                (sds((S, S * ecap), np.int32, shard),),
            )
            schedule_kernel(
                self.game, "serg", (self._mesh_key, ecap, wcap),
                self._egather_build(ecap, wcap),
                (
                    sds((S, S * ecap), np.int32, shard),
                    sds((S, S * ecap), np.uint32, shard),
                    sds((S, wcap), np.uint8, shard),
                    sds((S, wcap), np.int32, shard),
                    sds((1,), np.int32, repl),
                ),
            )
            schedule_kernel(
                self.game, "serp", (self._mesh_key, cap, ecap),
                self._ereply_build(cap, ecap),
                (
                    sds((S, cap), dt, shard),
                    sds((S, S * ecap), np.uint32, shard),
                    sds((S, cap * M), np.int32, shard),
                ),
            )

    def _resize_fn(self, in_cap: int, out_cap: int):
        """Per-shard slice/pad [S, in_cap] -> [S, out_cap], on device.

        Sorted-unique slices keep their real entries first, so slicing to
        the next capacity bucket (>= max per-shard count) is exact.
        """
        mesh = self.mesh

        def build(game):
            def per_shard(local):
                x = local[0]
                if out_cap <= in_cap:
                    y = jax.lax.slice(x, (0,), (out_cap,))
                else:
                    y = jnp.concatenate(
                        [
                            x,
                            jnp.full(out_cap - in_cap, game.sentinel,
                                     dtype=x.dtype),
                        ]
                    )
                return y[None]

            return shard_map(
                per_shard, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS)
            )

        return get_kernel(
            self.game, "srsz", (self._mesh_key, in_cap, out_cap), build
        )

    def _backward_fn(self, cap: int, window_caps: tuple, qcap: int):
        """Compiled backward step for one level against local window slices."""
        mesh, S = self.mesh, self.S
        n_windows = len(window_caps)

        def build(game):
            sm = search_method()  # resolved at cache-key time

            def per_shard(local, *window_flat):
                return _sharded_backward_step(game, S, qcap, local,
                                              window_flat, sm)

            return shard_map(
                per_shard,
                mesh=mesh,
                in_specs=(P(AXIS),) + (P(AXIS),) * (3 * n_windows),
                out_specs=(P(AXIS), P(AXIS), P(), P()),
                check_vma=False,  # psum/all_gather outputs ARE replicated
            )

        return get_kernel(
            self.game,
            "sbwd",
            (self._mesh_key, cap, tuple(window_caps), qcap),
            build,
            lowering=(search_method(),),  # lookup_window's search lowering
        )

    def _route_fn(self, cap: int, qcap: int):
        """Compiled streamed-backward phase 1 (see _sharded_route_step)."""
        mesh, S = self.mesh, self.S

        def build(game):
            def per_shard(local):
                return _sharded_route_step(game, S, qcap, local)

            return shard_map(
                per_shard,
                mesh=mesh,
                in_specs=P(AXIS),
                out_specs=(P(AXIS),) * 5 + (P(),),
                check_vma=False,  # all_gathered qcounts ARE replicated
            )

        return get_kernel(
            self.game, "srt", (self._mesh_key, cap, qcap), build
        )

    def _lookup_acc_fn(self, qcap: int, wcap: int):
        """Compiled streamed-backward phase 2 (one window block)."""
        mesh = self.mesh

        def build(game):
            sm = search_method()  # resolved at cache-key time

            def step(queries, acc, wstates, wvals, wrem):
                return _sharded_lookup_acc_step(queries, acc, wstates,
                                                wvals, wrem, sm)

            return shard_map(
                step,
                mesh=mesh,
                in_specs=(P(AXIS),) * 5,
                out_specs=P(AXIS),
            )

        return get_kernel(
            self.game, "sla", (self._mesh_key, qcap, wcap), build,
            lowering=(search_method(),),
        )

    def _reply_fn(self, cap: int, qcap: int):
        """Compiled streamed-backward phase 3 (see _sharded_reply_step)."""
        mesh, S = self.mesh, self.S

        def build(game):
            def per_shard(local, acc, s_owner, pos, order):
                return _sharded_reply_step(game, S, qcap, local, acc,
                                           s_owner, pos, order)

            return shard_map(
                per_shard,
                mesh=mesh,
                in_specs=(P(AXIS),) * 5,
                out_specs=(P(AXIS), P(AXIS), P()),
                check_vma=False,  # psum misses ARE replicated
            )

        return get_kernel(
            self.game, "srp", (self._mesh_key, cap, qcap), build
        )

    def _root_fn(self, cap: int):
        """Replicated (value, remoteness) of one state from a device triple.

        The FINISHED analog for multi-host runs: the root's answer leaves
        the device as a psum-replicated scalar pair, never as a host
        download of a cross-process sharded array.
        """
        mesh = self.mesh

        def build(game):
            def per_shard(states, values, rem, query):
                ts, tv, tr = states[0], values[0], rem[0]
                idx = jnp.clip(
                    jnp.searchsorted(ts, query[0]), 0, ts.shape[0] - 1
                )
                hit = ts[idx] == query[0]
                v = jnp.where(hit, tv[idx].astype(jnp.int32), 0)
                r = jnp.where(hit, tr[idx], 0)
                return (
                    jax.lax.psum(v, AXIS),
                    jax.lax.psum(r, AXIS),
                )

            return shard_map(
                per_shard,
                mesh=mesh,
                in_specs=(P(AXIS), P(AXIS), P(AXIS), P()),
                out_specs=(P(), P()),
                check_vma=False,  # psum outputs ARE replicated
            )

        return get_kernel(
            self.game, "sroot", (self._mesh_key, cap), build
        )

    def _merge_fn(self, pool_cap: int, child_cap: int):
        """Merge routed children of one target level into its pool, on device.

        Per shard: select children whose level_of == target (a replicated
        scalar arg, so one kernel serves every level), concat with the
        existing pool slice, sort-unique. Both inputs are per-shard sorted
        owner-consistent sets, so the output is too. Replaces the old
        host-side np.union1d pool merging (VERDICT r2 item 5).
        """
        mesh = self.mesh

        def build(game):
            # resolved at cache-key time
            mb, cm = use_merge_sort(), compact_method()

            def per_shard(pool, kids, target):
                p, c = pool[0], kids[0]
                lv = jnp.where(
                    c != game.sentinel, game.level_of(c), -1
                )
                sel = jnp.where(lv == target[0], c, game.sentinel)
                uniq, count = sort_unique(
                    jnp.concatenate([p, sel]), mb, cm
                )
                return uniq[None], jax.lax.all_gather(count, AXIS)

            return shard_map(
                per_shard,
                mesh=mesh,
                in_specs=(P(AXIS), P(AXIS), P()),
                out_specs=(P(AXIS), P()),
                check_vma=False,  # all_gathered counts ARE replicated
            )

        return get_kernel(
            self.game, "smrg", (self._mesh_key, pool_cap, child_cap), build,
            lowering=(backend_key(), compact_method()),
        )

    def _level_check_fn(self, cap: int):
        """Children-per-target-level histogram + contract check.

        Returns (bad, per_target[J]) replicated: `bad` counts children whose
        level violates (kmin, kmax] — a broken level_of/max_level_jump/
        num_levels contract, surfaced instead of silently dropping
        positions — and per_target[j] counts children at level kmin+1+j, so
        the merge loop skips target levels that received nothing.
        """
        mesh = self.mesh

        def build(game):
            J = game.max_level_jump

            def per_shard(kids, kmin, kmax):
                c = kids[0]
                valid = c != game.sentinel
                lv = jnp.where(valid, game.level_of(c), -1)
                bad = jnp.sum(
                    valid & ((lv <= kmin[0]) | (lv > kmax[0]))
                )
                per = jnp.stack(
                    [jnp.sum(lv == kmin[0] + 1 + j) for j in range(J)]
                )
                return jax.lax.psum(bad, AXIS), jax.lax.psum(per, AXIS)

            return shard_map(
                per_shard,
                mesh=mesh,
                in_specs=(P(AXIS), P(), P()),
                out_specs=(P(), P()),
                check_vma=False,  # psum outputs ARE replicated
            )

        return get_kernel(
            self.game, "schk", (self._mesh_key, cap), build
        )

    # ------------------------------------------------------ capacity planning

    def _initial_route_cap(self, cap: int) -> int:
        """First-try per-(src,dst) all_to_all capacity for a level of `cap`.

        Expected bucket load is cap*max_moves/S; the headroom factor
        (GAMESMAN_ROUTE_HEADROOM, default 2.0) absorbs owner skew.
        Overflow is detected exactly (per-destination counts) and retried
        at the exact size — tests shrink this estimate to force the spill
        path deterministically. At 1e8+ frontiers the route/sort buffers
        scale with S*S*route_cap, so on a fake mesh (all shards in ONE
        host's RAM) headroom 1.0 halves peak memory for the price of an
        occasional one-step retry: the r5 8-shard 5x6 witness was
        OOM-killed at its peak level with the 2x default (130 GB RSS on
        a 125 GB box) and fits with 1.0.
        """
        return bucket_size(
            max(64, int(self.route_headroom * cap * self.game.max_moves)
                // self.S),
            self.min_bucket,
        )

    # ----------------------------------------------------------------- phases

    def _seed(self, init) -> tuple[List[np.ndarray], np.ndarray]:
        """Owner-partition the starting state(s): one root, or a whole
        sorted frontier (the hybrid engine starts sharded BFS at its
        cutover level's reachable set)."""
        g = self.game
        arr = np.atleast_1d(np.asarray(init, dtype=g.state_dtype))
        shards = self._repartition(np.sort(arr) if arr.shape[0] > 1
                                   else arr)
        counts = np.array([a.shape[0] for a in shards], dtype=np.int64)
        return shards, counts

    def _forward_fast(self, init, start_level: int,
                      resume: Dict[int, list] | None = None,
                      ) -> Dict[int, _SLevel]:
        """Device-resident forward sweep for uniform_level_jump games.

        The frontier chains on device: each level's routed+dedup'd children
        (already per-shard sorted) are resized to the next capacity bucket
        without leaving HBM. Host work per level: one counts sync.

        With a checkpointer, every discovered level's shard rows are saved
        immediately (save_forward_level_shard; sealed per level by process
        0 post-barrier) so a death mid-discovery keeps the prefix; `resume`
        is that prefix ({level: per-shard arrays} at THIS shard count) and
        expansion continues from its deepest level. The consolidated
        end-of-forward snapshot still supersedes these files on completion
        — it alone supports shard-count-changing resumes.
        """
        g = self.game
        S = self.S
        if resume:
            ks = sorted(resume)
            if ks != list(range(ks[0], ks[-1] + 1)) or ks[0] != start_level:
                raise SolverError(
                    f"forward checkpoint levels {ks} are not contiguous "
                    f"from the root level {start_level} — stale checkpoint "
                    "directory?"
                )
            levels = {}
            for kk in ks:
                shards = [np.asarray(a, dtype=g.state_dtype)
                          for a in resume[kk]]
                levels[kk] = _SLevel(
                    np.array([a.shape[0] for a in shards], dtype=np.int64),
                    None, shards,
                )
            k = ks[-1]
            deep = levels[k]
            counts = deep.counts
            cap = bucket_size(int(counts.max()), self.min_bucket)
            frontier = jax.device_put(
                _pad_shards(deep.host, cap), self._sharding
            )
            deep.dev = frontier
        else:
            shards, counts = self._seed(init)
            cap = bucket_size(int(counts.max()), self.min_bucket)
            frontier = jax.device_put(_pad_shards(shards, cap),
                                      self._sharding)
            levels = {start_level: _SLevel(counts, frontier, shards)}
            k = start_level
            self._ckpt_forward_level(k, levels[k])
        stored_bytes = frontier.nbytes
        while True:
            t0 = time.perf_counter()
            self.progress = {
                "phase": "forward", "level": k, "rank": self.rank,
                "frontier": int(levels[k].counts.sum()),
            }
            # Level boundary: level k's incremental frontier (and edge)
            # files are already enqueued/sealed — a grace signal stops
            # HERE and resume re-expands from the deepest sealed level.
            self._check_preempt("forward", k)
            b0 = (self.bytes_routed, self.bytes_sorted)
            disp0 = self.dispatch_total
            route_cap = self._initial_route_cap(cap)
            eidx = slot = None
            while True:
                # The whole dispatch+counts-sync is the retried unit: a
                # transient collective failure re-dispatches from the
                # frontier, which stays referenced across the step.
                def _step(cap=cap, route_cap=route_cap, frontier=frontier):
                    if self.use_edges:
                        u, e, sl, c, sc = self._forward_fn(
                            cap, route_cap, provenance=True
                        )(frontier)
                    else:
                        u, c, sc = self._forward_fn(cap, route_cap)(frontier)
                        e = sl = None
                    return u, e, sl, c, int(np.asarray(sc).max())

                uniq, eidx, slot, count, max_sent = self._retry(
                    "sharded.forward", _step, level=k,
                    entry=lambda k=k: faults.fire("sharded.forward",
                                                  level=k),
                )
                if max_sent <= route_cap:
                    break
                self.spill_retries += 1
                route_cap = bucket_size(max_sent)
            item = np.dtype(g.state_dtype).itemsize
            compaction = compaction_sort_bytes(item)
            # Fused dedup changes the sort-operand denominator (ISSUE 14):
            # callback = one numpy radix pass over the routed block;
            # scatterinv = ONE pair sort + compaction instead of two.
            fz = fused_dedup_method() if fused_enabled() else None
            if self.use_edges:
                # States out + the uid reply riding back.
                self.bytes_routed += S * S * route_cap * (item + 4)
                if fz == "callback":
                    prov_bytes = item
                elif fz == "scatterinv":
                    prov_bytes = item + 4 + compaction
                else:
                    prov_bytes = provenance_sort_bytes(item, compaction)
                self.bytes_sorted += S * S * route_cap * prov_bytes
            else:
                self.bytes_routed += S * S * route_cap * item
                self.bytes_sorted += S * S * route_cap * (
                    item if fz == "callback" else item + compaction
                )
            counts = np.asarray(count).reshape(-1).astype(np.int64)
            total = int(counts.sum())
            if total == 0:
                self.status_tracker.forward_level(
                    k, int(levels[k].counts.sum()),
                    time.perf_counter() - t0,
                )
                flightrec.boundary("forward", k)
                break
            if self.use_edges:
                # Edges belong to the level just EXPANDED (they index into
                # level k+1's per-owner prefixes). Device-resident while
                # the store budget allows, host-spilled past it — the
                # backward step re-uploads spilled edges exactly like
                # spilled level states.
                cur = levels[k]
                cur.ecap = route_cap
                extra = eidx.nbytes + slot.nbytes
                to_disk = False
                if stored_bytes + extra <= self.device_store_bytes:
                    cur.eidx, cur.slot = eidx, slot
                    stored_bytes += extra
                else:
                    cur.eidx = _HostSpill.download(eidx)
                    cur.slot = _HostSpill.download(slot)
                    self.edges_bytes_spilled += extra
                    # Disk tier (ISSUE 11): when the host-RAM tier (the
                    # store cache budget) is exhausted too AND the edge
                    # files are being sealed anyway, keep NO resident
                    # copy — backward reloads them through the store,
                    # where the level schedule's readahead hints turn
                    # the loads into cache hits.
                    to_disk = (
                        self.checkpointer is not None
                        and self._host_spill_bytes + extra
                        > self.store.cache.budget_bytes
                    )
                    if not to_disk:
                        self._host_spill_bytes += extra
                self._ckpt_edges_level(k, cur)
                if to_disk:
                    # The save path extracted + enqueued its own host
                    # copies above; the sealed files are authoritative.
                    cur.eidx = cur.slot = None
                    self.edges_bytes_disk += extra
            if k + 1 >= g.num_levels:
                raise SolverError(
                    f"game {g.name}: children found at level {k + 1} but "
                    f"num_levels={g.num_levels} — level_of/num_levels "
                    "inconsistent"
                )
            next_cap = bucket_size(int(counts.max()), self.min_bucket)
            nxt = self._resize_fn(uniq.shape[-1], next_cap)(uniq)
            rec = _SLevel(counts, nxt, None)
            if stored_bytes + nxt.nbytes > self.device_store_bytes:
                # Device-store budget exhausted: keep this level on host only
                # (backward re-uploads it); the live frontier still chains on
                # device.
                rec.host_shards()
                rec.dev = None
            else:
                stored_bytes += nxt.nbytes
            levels[k + 1] = rec
            frontier = nxt
            cap = next_cap
            self._ckpt_forward_level(k + 1, rec)
            lvl_secs = time.perf_counter() - t0
            if self.logger is not None:
                self.logger.log(
                    {
                        "phase": "forward",
                        "level": k,
                        "frontier": int(levels[k].counts.sum()),
                        "children": total,
                        "shards": S,
                        "route_cap": route_cap,
                        "bytes_routed": self.bytes_routed - b0[0],
                        "bytes_sorted": self.bytes_sorted - b0[1],
                        "bytes_hbm": self.bytes_sorted - b0[1],
                        "dispatches": self.dispatch_total - disp0,
                        "secs": lvl_secs,
                    }
                )
            self.status_tracker.forward_level(
                k, int(levels[k].counts.sum()), lvl_secs
            )
            flightrec.boundary("forward", k)
            k += 1
        return levels

    def _forward_generic(self, init, start_level: int) -> Dict[int, _SLevel]:
        """Device-resident forward for multi-jump games (children span
        levels).

        Each expanded level's routed children are grouped by topological
        level and merged into per-level device pools ON DEVICE (one
        sort-unique merge per reachable target level — see _merge_fn); the
        old path downloaded every level's children and merged host pools
        with np.union1d. Host work per level is counts syncs only
        (VERDICT r2 item 5). Levels pop in ascending order, so every
        contribution to level L lands before L is expanded.
        """
        g = self.game
        S = self.S
        J = g.max_level_jump
        shards, counts = self._seed(init)
        cap0 = bucket_size(int(counts.max()), self.min_bucket)
        frontier0 = jax.device_put(_pad_shards(shards, cap0), self._sharding)
        levels: Dict[int, _SLevel] = {}
        #: level -> (dev [S, cap] per-shard sorted pool, np [S] counts)
        pools: Dict[int, tuple] = {start_level: (frontier0, counts)}
        stored_bytes = 0
        while pools:
            k = min(pools)
            t0 = time.perf_counter()
            self.progress = {"phase": "forward", "level": k,
                             "rank": self.rank}
            self._check_preempt("forward", k)
            b0 = (self.bytes_routed, self.bytes_sorted)
            disp0 = self.dispatch_total
            frontier, counts = pools.pop(k)
            rec = _SLevel(counts, frontier, None)
            levels[k] = rec
            # Pending (not yet popped) pools are live device state too —
            # count them against the budget when deciding whether this
            # retained level may stay resident.
            pending_bytes = sum(p.nbytes for p, _ in pools.values())
            if (stored_bytes + pending_bytes + frontier.nbytes
                    > self.device_store_bytes):
                rec.host_shards()
                rec.dev = None
            else:
                stored_bytes += frontier.nbytes
            cap = frontier.shape[1]
            route_cap = self._initial_route_cap(cap)
            while True:
                def _step(cap=cap, route_cap=route_cap, frontier=frontier):
                    u, c, sc = self._forward_fn(cap, route_cap)(frontier)
                    return u, c, int(np.asarray(sc).max())

                uniq, count, max_sent = self._retry(
                    "sharded.forward", _step, level=k,
                    entry=lambda k=k: faults.fire("sharded.forward",
                                                  level=k),
                )
                if max_sent <= route_cap:
                    break
                self.spill_retries += 1
                route_cap = bucket_size(max_sent)
            item = np.dtype(g.state_dtype).itemsize
            compaction = compaction_sort_bytes(item)
            fz = fused_dedup_method() if fused_enabled() else None
            self.bytes_routed += S * S * route_cap * item
            self.bytes_sorted += S * S * route_cap * (
                item if fz == "callback" else item + compaction
            )
            ccounts = np.asarray(count).reshape(-1)
            total = int(ccounts.sum())
            if total > 0:
                ccap = bucket_size(int(ccounts.max()), self.min_bucket)
                kmax = min(k + J, g.num_levels - 1)

                # Collective-safe retried unit (GM603): the resize +
                # level-check kernels route children through an
                # all_to_all/psum — `uniq` stays referenced across the
                # step, so re-dispatch is idempotent.
                def _check_step(ccap=ccap, uniq=uniq, k=k, kmax=kmax):
                    children = self._resize_fn(uniq.shape[-1], ccap)(uniq)
                    bad, per_target = self._level_check_fn(ccap)(
                        children,
                        np.full(1, k, np.int32),
                        np.full(1, kmax, np.int32),
                    )
                    return children, int(bad), np.asarray(per_target)

                children, bad, per_target = self._retry(
                    "sharded.forward", _check_step, level=k,
                    entry=lambda k=k: faults.fire("sharded.forward",
                                                  level=k),
                )
                if bad > 0:
                    raise SolverError(
                        f"game {g.name}: {bad} children outside levels "
                        f"({k}, {kmax}] — level_of/max_level_jump/"
                        "num_levels inconsistent"
                    )
                empty_pool = None
                for j in range(1, J + 1):
                    L = k + j
                    if L >= g.num_levels:
                        break
                    if int(per_target[j - 1]) == 0:
                        continue  # no child landed here; skip the merge
                    pool, _ = pools.get(L, (None, None))
                    if pool is None:
                        if empty_pool is None:
                            empty_pool = jax.device_put(
                                _pad_shards(
                                    [np.empty(0, g.state_dtype)] * S,
                                    bucket_size(1, self.min_bucket),
                                ),
                                self._sharding,
                            )
                        pool = empty_pool

                    # Same discipline for the merge dispatch: inputs
                    # (pool, children) are held across the step, the
                    # pools[L] assignment lands only on success.
                    def _merge_step(pool=pool, children=children, L=L,
                                    ccap=ccap):
                        merged, mcount = self._merge_fn(
                            pool.shape[1], ccap
                        )(pool, children, np.full(1, L, np.int32))
                        mcounts = np.asarray(mcount).reshape(-1) \
                            .astype(np.int64)
                        mcap = bucket_size(int(mcounts.max()),
                                           self.min_bucket)
                        return (
                            self._resize_fn(merged.shape[-1], mcap)(merged),
                            mcounts,
                        )

                    pools[L] = self._retry(
                        "sharded.forward", _merge_step, level=k,
                        entry=lambda k=k: faults.fire("sharded.forward",
                                                      level=k),
                    )
                    self.bytes_sorted += (
                        S * (pool.shape[1] + ccap) * (item + compaction)
                    )
            lvl_secs = time.perf_counter() - t0
            if self.logger is not None:
                self.logger.log(
                    {
                        "phase": "forward",
                        "level": k,
                        "frontier": int(counts.sum()),
                        "children": total,
                        "shards": S,
                        "route_cap": route_cap,
                        "bytes_routed": self.bytes_routed - b0[0],
                        "bytes_sorted": self.bytes_sorted - b0[1],
                        "bytes_hbm": self.bytes_sorted - b0[1],
                        "dispatches": self.dispatch_total - disp0,
                        "secs": lvl_secs,
                    }
                )
            self.status_tracker.forward_level(k, int(counts.sum()),
                                              lvl_secs)
            flightrec.boundary("forward", k)
        return levels

    def _run_backward_step(self, stacked, cap: int, window_caps: tuple,
                           window_flat) -> tuple:
        """One backward kernel call with the qcap overflow-retry loop."""
        qcap = self._initial_route_cap(cap) if window_caps else 0
        while True:
            values, rem, misses, qcounts = self._backward_fn(
                cap, window_caps, qcap
            )(stacked, *window_flat)
            if qcap == 0:
                break
            max_sent = int(np.asarray(qcounts).max())
            if max_sent <= qcap:
                break
            self.spill_retries += 1
            qcap = bucket_size(max_sent)
        if qcap:
            S = self.S
            item = np.dtype(self.game.state_dtype).itemsize
            # Queries out (state bytes) + packed cells back.
            self.bytes_routed += S * S * qcap * (item + 4)
            if search_method() == "sort":
                # Sort-merge join operands + fused payload gather w/ idx.
                self.bytes_sorted += (
                    S * (S * qcap + sum(window_caps)) * (item + 4)
                )
                self.bytes_gathered += S * S * qcap * 12
            else:
                # Binary search: no join sort, one payload gather per query
                # (log2 traversal reads not modeled).
                self.bytes_gathered += S * S * qcap * 8
        return values, rem, misses

    def _run_backward_step_streamed(self, stacked, cap: int, windows):
        """One backward step with the window STREAMED through HBM in blocks.

        windows: list of (states, values, remoteness) _HostSpill triples,
        each [S, wcapL] (padded, per-shard-sorted slices). Route once, then
        per window block: upload [S, wblock] slices, look up, accumulate
        packed cells; reply once. Per-shard window memory is O(wblock);
        queries/bookkeeping are O(cap·M) — both independent of level size.

        Known cost at extreme scale: when the RESOLVING side also blocks
        (_resolve_blocked_streamed), the window is re-uploaded once per
        resolve block — host->device traffic x (level/backward_block). The
        fix direction is a rotating HBM pool of window blocks shared across
        resolve blocks; not needed below 7x6 scale.
        """
        qcap = self._initial_route_cap(cap)
        while True:
            queries, acc, s_owner, pos, order, qcounts = self._route_fn(
                cap, qcap
            )(stacked)
            max_sent = int(np.asarray(qcounts).max())
            if max_sent <= qcap:
                break
            self.spill_retries += 1
            qcap = bucket_size(max_sent)
        S = self.S
        item = np.dtype(self.game.state_dtype).itemsize
        self.bytes_routed += S * S * qcap * (item + 4)
        for ws, wv, wr in windows:
            wb = min(self.window_block, ws.cap)
            for off in range(0, ws.cap, wb):
                blk = (ws.block(off, wb), wv.block(off, wb),
                       wr.block(off, wb))
                acc = self._lookup_acc_fn(qcap, wb)(queries, acc, *blk)
                self.window_stream_blocks += 1
                if search_method() == "sort":
                    self.bytes_sorted += S * (S * qcap + wb) * (item + 4)
                    self.bytes_gathered += S * S * qcap * 12
                else:
                    self.bytes_gathered += S * S * qcap * 8
        return self._reply_fn(cap, qcap)(stacked, acc, s_owner, pos, order)

    def _blocked_loop(self, stacked, step):
        """Column-block the resolving side: run `step(block_slice, block)`
        per block and concatenate. Shared by the resident and streamed
        resolvers — the block arithmetic must stay identical for their
        kernel keys to match the pre-scheduled shapes."""
        cap = stacked.shape[1]
        # Power-of-two floor: divides the (power-of-two) cap exactly.
        block = 1 << max(self.backward_block, 1).bit_length() - 1
        if cap <= block:
            return step(stacked, cap)
        values, rems = [], []
        misses = None
        for off in range(0, cap, block):
            v, r, m = step(stacked[:, off : off + block], block)
            values.append(v)
            rems.append(r)
            # Device-side accumulation; synced only under --paranoid.
            misses = m if misses is None else misses + m
        return (
            jnp.concatenate(values, axis=1),
            jnp.concatenate(rems, axis=1),
            misses,
        )

    def _resolve_blocked(self, stacked, window_caps: tuple, window_flat):
        """Backward-resolve a level, in column blocks when it is wide.

        Per-shard temporaries (child blocks, routing buffers) scale with
        the block, not the level — the HBM bound the 6x6/6x7 capacity plan
        relies on (docs/ARCHITECTURE.md). The window stays resident here;
        levels wider than window_block take _resolve_blocked_streamed
        instead, which streams the window through HBM too.
        """
        return self._blocked_loop(
            stacked,
            lambda blk, c: self._run_backward_step(
                blk, c, window_caps, window_flat
            ),
        )

    def _resolve_blocked_streamed(self, stacked, windows):
        """Streamed-window resolve, also blocking the resolving side.

        Composes both blockings: per-shard peak is O(resolve block) for
        children/routing and O(window block) for the window — the full 7x6
        memory shape (docs/ARCHITECTURE.md capacity plan).
        """
        return self._blocked_loop(
            stacked,
            lambda blk, c: self._run_backward_step_streamed(blk, c, windows),
        )

    def _repartition(self, states: np.ndarray) -> List[np.ndarray]:
        """Split a sorted global state array into per-shard sorted arrays."""
        owners = owner_shard_np(states, self.S)
        return [states[owners == s] for s in range(self.S)]

    def _backward(self, levels: Dict[int, _SLevel], root_level: int,
                  init) -> Dict[int, LevelTable]:
        """Deepest-first owner-routed resolve; unified fast/generic path.

        The window cache holds the device triples (states, values,
        remoteness) of the last `max_level_jump` resolved levels — each
        P(AXIS)-sharded, so per-shard window memory stays O(level/S).

        With store_tables=False only the root level's table is materialized
        on host (plus whatever the checkpointer persists) — the big-run mode
        where accumulating every level's table in host RAM is the remaining
        O(total-positions) cost (docs/ARCHITECTURE.md capacity plan).
        """
        g = self.game
        S = self.S
        # Forward's seals (edges + frontier levels) must all be visible
        # before the backward reads edge_level_info/completed_levels.
        self._flush_seals()
        resolved: Dict[int, LevelTable] = {}
        dev_cache: Dict[int, tuple] = {}
        # Window levels wider than window_block per shard live here as host
        # numpy triples and are streamed back through HBM in blocks during
        # lookup (per-shard window memory O(block), not O(level/S)).
        host_cache: Dict[int, tuple] = {}
        completed = (
            set(self.checkpointer.completed_levels())
            if self.checkpointer is not None
            else set()
        )
        if self.precompile and self.use_edges:
            # All edge-backward shapes are known now; compile them in the
            # background, deepest-first, while the deep levels execute.
            self._schedule_backward_edges(levels, completed)
        order = sorted(levels, reverse=True)
        for i, k in enumerate(order):
            b0 = (self.bytes_routed, self.bytes_sorted, self.bytes_gathered)
            io0 = self.store.stats()["io_wait_secs"]
            rec = levels[k]
            self.progress = {
                "phase": "backward", "level": k, "rank": self.rank,
                "n": int(rec.counts.sum()),
            }
            self._check_preempt("backward", k)
            # Batched readahead from the level schedule: while THIS
            # level resolves, the store's pool decodes the NEXT level's
            # sealed checkpoint/edge shards — the solve thread's loads
            # one iteration from now become cache hits (today's
            # synchronous spill loads, overlapped away).
            if i + 1 < len(order):
                self._hint_backward_level(
                    order[i + 1], levels[order[i + 1]], completed
                )
            from_checkpoint = k in completed
            # Edge-cached resolve when this level's forward edges exist
            # (in memory, spilled, or sealed in the checkpoint dir) AND the
            # deeper level they index is in the window cache; every other
            # level takes the lookup join — the structural fallback that
            # keeps pre-edge checkpoints and generic-path games solving.
            want_edges = (
                self.use_edges and not from_checkpoint
                and ((k + 1) in dev_cache or (k + 1) in host_cache)
                and self._edges_available(k, rec)
            )
            mode = "edges" if want_edges else "lookup"
            # Distinct span names so a mixed solve's JSONL/registry shows
            # exactly which levels ran which backward (docs/OBSERVABILITY);
            # the span starts BEFORE the budget-evicted level's re-upload
            # and the edge load, like the t0 it replaced, so per-level
            # secs reconcile with the solve-level secs_backward.
            sp = Span("backward_edges" if want_edges else "backward",
                      logger=self.logger, level=k)
            n_max = int(rec.counts.max()) if rec.counts.size else 0
            if rec.dev is None:
                cap = bucket_size(n_max, self.min_bucket)
                rec.dev = jax.device_put(
                    _pad_shards(rec.host_shards(), cap), self._sharding
                )
            cap = rec.dev.shape[1]
            edges = self._load_edges(k, rec, cap) if want_edges else None
            if edges is None:
                mode = "lookup"  # rare torn/mismatched edge files degrade
            loaded = None
            if from_checkpoint:
                try:
                    loaded = self._load_checkpointed_level(
                        k, rec, cap, root_level
                    )
                except TORN_NPZ_ERRORS as e:
                    # Torn or crc-mismatching sealed level: quarantine and
                    # degrade to a recompute — the frontier is still known
                    # and the deeper window is already resolved. (The
                    # lookup join, not edges: the edge decision was taken
                    # before the load and this path is rare.)
                    self.checkpointer.quarantine_and_log(k, e, self.logger)
                    from_checkpoint = False
                    mode = "lookup"
            if loaded is not None:
                pv, pr, table = loaded
                values_dev = jax.device_put(pv, self._sharding)
                rem_dev = jax.device_put(pr, self._sharding)
            elif edges is not None:
                # Edge-cached resolve: collectives + gathers on stored
                # indices — no search, no re-expansion, no join sort
                # (bytes_sorted contribution: zero).
                eidx, slot, ecap = edges

                def _resolve_e(eidx=eidx, slot=slot, ecap=ecap, rec=rec,
                               k=k):
                    return self._resolve_edges_level(
                        rec, eidx, slot, ecap,
                        dev_cache.get(k + 1), host_cache.get(k + 1),
                    )

                values_dev, rem_dev, misses = self._retry(
                    "sharded.backward", _resolve_e, level=k,
                    entry=lambda k=k: faults.fire("sharded.backward",
                                                  level=k),
                )
                self.backward_edges_levels += 1
                del eidx, slot
                rec.eidx = rec.slot = None  # release the edge arrays
                if self.paranoid and int(_fetch_global(misses).sum()) > 0:
                    raise SolverError(
                        f"level {k}: consistency failures (zero-move "
                        "non-primitive positions)"
                    )
                table = self._materialize_level(
                    k, rec, values_dev, rem_dev, root_level
                )
            else:
                window_levels = [
                    k + j
                    for j in range(1, g.max_level_jump + 1)
                    if (k + j) in dev_cache or (k + j) in host_cache
                ]
                if all(L in dev_cache for L in window_levels):
                    window_caps = tuple(
                        dev_cache[L][0].shape[1] for L in window_levels
                    )
                    window_flat = []
                    for L in window_levels:
                        window_flat.extend(dev_cache[L])

                    def _resolve_l(rec=rec, window_caps=window_caps,
                                   window_flat=window_flat):
                        return self._resolve_blocked(
                            rec.dev, window_caps, window_flat
                        )

                    values_dev, rem_dev, misses = self._retry(
                        "sharded.backward", _resolve_l, level=k,
                        entry=lambda k=k: faults.fire("sharded.backward",
                                                      level=k),
                    )
                else:
                    # At least one window level was spilled: stream ALL of
                    # them (a resident one is downloaded once — mixing
                    # resident and streamed lookups would double the kernel
                    # shapes for a rare multi-jump corner).
                    windows = []
                    for L in window_levels:
                        if L not in host_cache:
                            # Move (not copy) the resident level to the host
                            # cache: one download, no double memory, and
                            # shallower levels that window on L reuse it.
                            host_cache[L] = tuple(
                                _HostSpill.download(a) for a in dev_cache[L]
                            )
                            del dev_cache[L]
                        windows.append(host_cache[L])

                    def _resolve_s(rec=rec, windows=windows):
                        return self._resolve_blocked_streamed(
                            rec.dev, windows
                        )

                    values_dev, rem_dev, misses = self._retry(
                        "sharded.backward", _resolve_s, level=k,
                        entry=lambda k=k: faults.fire("sharded.backward",
                                                      level=k),
                    )
                if self.paranoid and int(_fetch_global(misses).sum()) > 0:
                    raise SolverError(
                        f"level {k}: consistency failures (missed child "
                        "lookups or zero-move non-primitive positions)"
                    )
                table = self._materialize_level(
                    k, rec, values_dev, rem_dev, root_level
                )
            if table is not None and (self.store_tables or k == root_level):
                resolved[k] = table
            if k == root_level:
                # The root answer leaves the device replicated (multi-host
                # safe) — the only result a big-run solve must produce.
                # The kernel psums across shards, so the dispatch is
                # collective-safe-retried like every other step (GM603):
                # its inputs stay referenced, re-dispatch is idempotent.
                def _root_step(cap=cap, rec=rec, values_dev=values_dev,
                               rem_dev=rem_dev):
                    v, r = self._root_fn(cap)(
                        rec.dev, values_dev, rem_dev,
                        jnp.full((1,), init, dtype=g.state_dtype),
                    )
                    return int(v), int(r)

                self._root_answer = self._retry(
                    "sharded.backward", _root_step, level=k,
                    entry=lambda k=k: faults.fire("sharded.backward",
                                                  level=k),
                )
            if self.checkpointer is not None and not from_checkpoint:
                # One npz per addressable shard — each multi-host process
                # writes only the shards it owns, nothing global assembles.
                self._checkpoint_level_shards(k, rec, values_dev, rem_dev)
            if cap <= self.window_block:
                dev_cache[k] = (rec.dev, values_dev, rem_dev)
            else:
                # Too wide to keep resident as a window: spill to host (via
                # addressable shards — multi-host safe), to be streamed back
                # in blocks by shallower levels' lookups.
                host_cache[k] = tuple(
                    _HostSpill.download(a)
                    for a in (rec.dev, values_dev, rem_dev)
                )
            rec.dev = None  # the cache owns the device copy now
            rec.eidx = rec.slot = None  # edges can never be read again
            if not self.store_tables:
                rec.host = None  # bound host RAM in big-run mode
            for done in [d for d in dev_cache if d > k + g.max_level_jump]:
                del dev_cache[done]
            for done in [d for d in host_cache if d > k + g.max_level_jump]:
                del host_cache[done]
            sp.end(
                n=int(rec.counts.sum()),
                shards=S,
                mode=mode,
                resumed=from_checkpoint,
                bytes_routed=self.bytes_routed - b0[0],
                bytes_sorted=self.bytes_sorted - b0[1],
                bytes_gathered=self.bytes_gathered - b0[2],
                bytes_hbm=(self.bytes_sorted - b0[1])
                + (self.bytes_gathered - b0[2]),
                io_wait_secs=round(
                    self.store.stats()["io_wait_secs"] - io0, 6
                ),
            )
            self.status_tracker.backward_level(
                k, int(rec.counts.sum()), sp.secs,
                resumed=from_checkpoint,
            )
            flightrec.boundary("backward", k)
        return resolved

    def _hint_backward_level(self, k: int, rec, completed) -> None:
        """Readahead hints for one upcoming backward level: its sealed
        checkpoint shards (resume) and/or its disk-tiered edge shards.
        Hinting is advisory — an evicted or rejected hint degrades to
        the synchronous sealed read, never a wrong answer."""
        ck = self.checkpointer
        if ck is None or not hasattr(ck, "prefetch_level_shards"):
            return  # stubbed checkpointers in tests: skip readahead
        manifest = ck.load_manifest()
        if k in completed:
            if manifest.get("sharded_levels", {}).get(str(k)) == self.S:
                ck.prefetch_level_shards(k, self.S, manifest)
            else:
                ck.prefetch_level(k)
        elif self.use_edges and rec.eidx is None:
            info = manifest.get("edge_levels", {}).get(str(k))
            if info and info.get("shards") == self.S:
                ck.prefetch_edges_level(k, self.S, manifest)

    def _load_checkpointed_level(self, k: int, rec, cap: int,
                                 root_level: int):
        """Restart-from-level: (values [S, cap], remoteness [S, cap],
        table|None) of a sealed level, validated against the discovered
        frontier. Per-shard files at a matching shard count load
        shard-to-shard with no global assembly; a global file (or a
        different shard count) goes through assemble + repartition.
        Raises a TORN_NPZ_ERRORS member on unreadable/corrupt files
        (caller quarantines + recomputes) and SolverError on a genuine
        frontier mismatch (stale directory — still fatal)."""
        g = self.game
        S = self.S
        pv = np.full((S, cap), UNDECIDED, dtype=np.uint8)
        pr = np.zeros((S, cap), dtype=np.int32)
        table = None
        manifest = self.checkpointer.load_manifest()
        sealed_count = manifest.get("sharded_levels", {}).get(str(k))
        if sealed_count == S or (
            sealed_count is not None and reshard_enabled()
        ):
            shards = rec.host_shards()
            if sealed_count == S:
                per_shard = [
                    self.checkpointer.load_level_shard(k, s, manifest)
                    for s in range(S)
                ]
            else:
                # Reshard-on-resume (ISSUE 13): stream the level sealed
                # at S_old shards into THIS run's S shards — one sealed
                # file decoded at a time through the block store, rows
                # re-partitioned by the owner hash, packed cells riding
                # along row-aligned. No global table ever assembles
                # (the pre-elastic path paid load_level's full sort).
                if hasattr(self.checkpointer, "prefetch_level_shards"):
                    # Stubbed checkpointers in tests may not expose
                    # readahead; hints are advisory anyway.
                    self.checkpointer.prefetch_level_shards(
                        k, sealed_count, manifest
                    )

                def _one(s):
                    st, cells = self.checkpointer.load_level_shard(
                        k, s, manifest
                    )
                    return st.astype(g.state_dtype), cells

                per_shard = reshard_shard_stream(_one, sealed_count, S)
            loaded = []
            for s in range(S):
                st, cells = per_shard[s]
                if st.shape[0] != shards[s].shape[0] or not (
                    st.astype(g.state_dtype) == shards[s]
                ).all():
                    raise SolverError(
                        f"checkpointed level {k} (shard {s}) does "
                        "not match the discovered frontier — stale "
                        "checkpoint directory?"
                    )
                v, r = unpack_cells_np(cells)
                pv[s, : v.shape[0]] = v
                pr[s, : r.shape[0]] = r
                loaded.append((st, v, r))
            if self.store_tables or (
                k == root_level and self.materialize_root_table
            ):
                # Assemble from the shards already in hand (a
                # load_level call would re-read every file).
                states = np.concatenate([t[0] for t in loaded])
                order = np.argsort(states)
                table = LevelTable(
                    states=states[order].astype(g.state_dtype),
                    values=np.concatenate([t[1] for t in loaded])[order],
                    remoteness=np.concatenate(
                        [t[2] for t in loaded]
                    )[order],
                )
        else:
            table = self.checkpointer.load_level(k)
            table = LevelTable(
                states=np.asarray(table.states, dtype=g.state_dtype),
                values=table.values,
                remoteness=table.remoteness,
            )
            shards = rec.host_shards()
            expected = np.sort(np.concatenate(shards)) if shards \
                else np.empty(0, g.state_dtype)
            if table.states.shape[0] != expected.shape[0] or not (
                table.states == expected
            ).all():
                raise SolverError(
                    f"checkpointed level {k} does not match the "
                    "discovered frontier — stale checkpoint "
                    "directory?"
                )
            owners = owner_shard_np(table.states, S)
            for s in range(S):
                sel = owners == s
                pv[s, : sel.sum()] = table.values[sel]
                pr[s, : sel.sum()] = table.remoteness[sel]
        return pv, pr, table

    def _materialize_level(self, k: int, rec, values_dev, rem_dev,
                           root_level: int):
        """Global LevelTable of one resolved level, or None in big-run mode.

        Checkpointing no longer forces a global table: levels are
        checkpointed per shard (VERDICT r2 item 4), so big-run + checkpoint
        does zero global materialization. The hybrid engine's boundary join
        needs ITS root level (= the cutover boundary) as a table even in
        big-run mode — in plain solves the root answer instead leaves the
        device via _root_fn and no table materializes.
        """
        if not (self.store_tables or (
                k == root_level and self.materialize_root_table)):
            return None  # big-run mode: nothing leaves the device
        # Global table for this level (kept sharded on device during the
        # solve; materialized for the result).
        shards = rec.host_shards()
        values = _fetch_global(values_dev)
        remoteness = _fetch_global(rem_dev)
        gs, gv, gr = [], [], []
        for s in range(self.S):
            n = int(rec.counts[s])
            gs.append(shards[s])
            gv.append(values[s, :n])
            gr.append(remoteness[s, :n])
        states = np.concatenate(gs)
        order = np.argsort(states)
        return LevelTable(
            states=states[order],
            values=np.concatenate(gv)[order],
            remoteness=np.concatenate(gr)[order],
        )

    def _edges_available(self, k: int, rec) -> bool:
        """Cheap pre-Span predicate: will _load_edges plausibly succeed?

        In-memory edges (device or spilled), or sealed checkpoint files at
        this shard count. The full geometry validation and the actual
        reads happen in _load_edges; a rare torn/mismatched file degrades
        the level to the lookup join mid-span, recorded in its `mode`
        field.
        """
        if rec.eidx is not None:
            return True
        if self.checkpointer is None:
            return False
        info = self.checkpointer.edge_level_info(k)
        if info and info.get("shards") != self.S:
            # Sealed at a foreign shard count: the eidx/slot maps index
            # into per-owner prefixes that no longer exist at this
            # geometry and CANNOT re-map — this level degrades to the
            # lookup backward (the per-level structural fallback), and
            # the count is the elastic-resume observable.
            self.edges_geometry_fallback_levels += 1
            return False
        return bool(info)

    def _load_edges(self, k: int, rec, cap: int):
        """Device-resident (eidx, slot, ecap) of level k's edges, or None.

        In-memory edges win (device arrays as-is; host-spilled ones
        re-upload whole, exactly like a spilled level's states). Otherwise
        sealed per-(level, shard) edge files from the checkpoint directory
        — an interrupted run resumed from its frontier snapshot — load when
        their shard count and slot geometry match this run. Anything
        missing, torn, or mismatched degrades to None and the caller falls
        back to the lookup backward: a pre-edge checkpoint keeps resuming.
        """
        if rec.eidx is not None:
            if isinstance(rec.eidx, _HostSpill):
                return (rec.eidx.block(0, rec.eidx.cap),
                        rec.slot.block(0, rec.slot.cap), rec.ecap)
            return rec.eidx, rec.slot, rec.ecap
        if self.checkpointer is None:
            return None
        manifest = self.checkpointer.load_manifest()
        info = manifest.get("edge_levels", {}).get(str(k))
        if (not info or info.get("shards") != self.S
                or info.get("slot_len") != cap * self.game.max_moves):
            return None
        ecap = int(info["ecap"])
        from gamesmanmpi_tpu.utils.checkpoint import TORN_NPZ_ERRORS

        try:
            es, ss = [], []
            for s in range(self.S):
                e, sl = self.checkpointer.load_edges_shard(k, s, manifest)
                es.append(np.asarray(e, dtype=np.int32))
                ss.append(np.asarray(sl, dtype=np.int32))
        except TORN_NPZ_ERRORS:
            return None  # torn edge files: degrade to the lookup join
        if any(e.shape[0] != self.S * ecap for e in es) or any(
                sl.shape[0] != cap * self.game.max_moves for sl in ss):
            return None
        return (jax.device_put(np.stack(es), self._sharding),
                jax.device_put(np.stack(ss), self._sharding), ecap)

    def _resolve_edges_level(self, rec, eidx, slot, ecap: int, wdev,
                             wspill):
        """Resolve one level from stored edges (the SEND_BACK analog with
        the search deleted): all_to_all the stored indices, gather packed
        cells on the owners, all_to_all the reply, combine via the stored
        slot map. No re-expansion, no join — bytes_sorted contribution is
        zero by construction.

        wdev: the deeper level's resident (states, values, remoteness)
        device triple, or None when it was host-spilled — then wspill is
        its _HostSpill triple and the gather streams value/remoteness
        blocks through HBM (the same window_block mechanism as the lookup
        path, but only the 5-byte cells stream — never the states).
        """
        S = self.S
        # The off operand must carry the replicated sharding the scheduled
        # AOT executables were compiled for (plain np arrays would not).
        repl = NamedSharding(self.mesh, P())
        q, acc = self._eroute_fn(ecap)(eidx)
        self.bytes_routed += S * S * ecap * 4  # i32 index queries out
        if wdev is not None:
            _, wv, wr = wdev
            acc = self._egather_fn(ecap, wv.shape[1])(
                q, acc, wv, wr,
                jax.device_put(np.zeros(1, np.int32), repl),
            )
            self.bytes_gathered += S * S * ecap * 8  # idx read + cell
        else:
            _, wv, wr = wspill
            wb = min(self.window_block, wv.cap)
            for off in range(0, wv.cap, wb):
                acc = self._egather_fn(ecap, wb)(
                    q, acc, wv.block(off, wb), wr.block(off, wb),
                    jax.device_put(np.full(1, off, np.int32), repl),
                )
                self.window_stream_blocks += 1
                self.bytes_gathered += S * S * ecap * 8
        self.bytes_routed += S * S * ecap * 4  # packed cells back
        return self._ereply_fn(rec.dev.shape[1], ecap)(rec.dev, acc, slot)

    def _shard_ranks(self) -> List[int]:
        """shard index -> owning process rank (all zeros single-host):
        the rank-set stamp each seal records so resume can tell WHICH
        process was responsible for a torn or missing shard file."""
        return [int(d.process_index) for d in self.mesh.devices.flat]

    @staticmethod
    def _shard_id(shard) -> int:
        """Global shard index of an addressable shard.

        A 1-device sharding reports index (slice(None), ...) — start is
        None, meaning offset 0 (this crashed num_shards=1 checkpointing
        when formatted into a filename).
        """
        return shard.index[0].start or 0

    def _shard_rows(self, rec, s: int):
        """One shard's real rows of a level, downloading only that shard.

        Uses addressable shards when the level is device-resident (multi-
        host: a process can only ever reach its own shards), else the host
        copy. Returns None when shard s is not addressable here.
        """
        if rec.dev is not None:
            for sh in rec.dev.addressable_shards:
                if self._shard_id(sh) == s:
                    return np.asarray(sh.data)[0][: int(rec.counts[s])]
            return None
        if self.num_processes > 1:
            # Host-resident level under multi-process execution (a
            # resumed checkpoint prefix, or a budget spill fetched via
            # the gather collective): every rank holds the full copy, so
            # write-ownership follows the mesh — the rank owning the
            # shard's device writes its file, everyone else defers. One
            # writer per shard, no racy duplicate snapshot files.
            if self._shard_ranks()[s] != self.rank:
                return None
        return rec.host_shards()[s]

    @staticmethod
    def _sync_processes(tag: str) -> None:
        """Barrier across processes before sealing a checkpoint manifest —
        process 0 must not mark shard sets complete while peers still
        write (torn checkpoints on preemption otherwise)."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(tag)

    def _ckpt_forward_level(self, k: int, rec) -> None:
        """Incrementally checkpoint one just-discovered level's shards.

        Forward alone outlasts the preemption/MTBF horizon at big-board
        scale; per-level saves keep the discovered prefix on a death
        mid-sweep (the single-device engine does the same). Each process
        writes only its addressable shards; process 0 seals the level after
        the barrier, so a torn level is never listed in the manifest.
        """
        if self.checkpointer is None:
            return
        tickets: List = []
        for s in range(self.S):
            rows = self._shard_rows(rec, s)
            if rows is not None:
                self._count_ckpt_bytes(
                    self.checkpointer.save_forward_level_shard(k, s, rows),
                    tickets,
                )

        def _seal(k=k):
            self._sync_processes(f"forward_level_{k}_shards_written")
            if jax.process_index() == 0:
                self.checkpointer.finish_forward_level(
                    k, self.S, ranks=self._shard_ranks(), drain=False
                )

        self._seal_after_writes(tickets, _seal)

    def _checkpoint_frontier_shards(self, levels) -> None:
        """Per-shard frontier snapshot files, one shard at a time.

        No global frontier array assembles anywhere (VERDICT r2 item 4):
        each (level, shard) row set downloads individually, peak host
        memory is one shard's frontiers, and under multi-host each process
        writes only the shards its devices own (process 0 seals the
        manifest).
        """
        self._flush_seals()  # the consolidated snapshot supersedes them
        tickets: List = []
        for s in range(self.S):
            pools = {}
            for k, rec in levels.items():
                rows = self._shard_rows(rec, s)
                if rows is not None:
                    pools[k] = rows
            if pools or jax.process_count() == 1:
                self._count_ckpt_bytes(
                    self.checkpointer.save_frontier_shard(s, pools),
                    tickets,
                )
        # Once-per-solve seal: run it eagerly (no pipelining partner).
        self._run_seal(tickets, lambda: None)
        self._sync_processes("frontier_shards_written")
        if jax.process_index() == 0:
            self.checkpointer.finish_frontier_shards(self.S, drain=False)

    def _checkpoint_level_shards(self, k: int, rec, values_dev,
                                 rem_dev) -> None:
        """Checkpoint one resolved level as per-shard npz files.

        Downloads via addressable shards (multi-host: each process sees and
        writes only its own devices' rows); the shard count is recorded in
        the manifest by process 0 so resume can validate/repartition.
        """

        def rows(arr):
            return {
                self._shard_id(s): np.asarray(s.data)[0]
                for s in arr.addressable_shards
            }

        sv, sr, ss = rows(values_dev), rows(rem_dev), rows(rec.dev)
        tickets: List = []
        for s, states in ss.items():
            n = int(rec.counts[s])
            cells = pack_cells_np(sv[s][:n], sr[s][:n])
            self._count_ckpt_bytes(
                self.checkpointer.save_level_shard(k, s, states[:n], cells),
                tickets,
            )

        def _seal(k=k):
            self._sync_processes(f"level_{k}_shards_written")
            if jax.process_index() == 0:
                self.checkpointer.finish_level_shards(
                    k, self.S, ranks=self._shard_ranks(), drain=False
                )

        self._seal_after_writes(tickets, _seal)

    def _count_ckpt_bytes(self, sizes, tickets=None) -> None:
        """Fold one checkpoint write's result into the run totals (stats
        ckpt_bytes_raw/ckpt_bytes_stored). ``sizes`` is a WriteTicket
        (write-behind — resolved later, when its seal waits on it; the
        ``tickets`` list collects it), a (raw, stored) pair (inline
        write), or None — wrapped/stubbed checkpointers (the resume
        tests' recording shims) may return None — skip, don't crash a
        solve over bookkeeping."""
        if not sizes:
            return
        if isinstance(sizes, WriteTicket):
            if tickets is not None:
                tickets.append(sizes)
            return
        raw, stored = sizes
        self.ckpt_bytes_raw += int(raw)
        self.ckpt_bytes_stored += int(stored)

    # ------------------------------------------------ seal pipelining
    # Payload writes ride the store's write-behind queue; seals (manifest
    # RMW) stay on the SOLVE thread, deferred one level: the seal for
    # level k's files runs when level k-1's checkpoint call arrives (or
    # at the next phase boundary), after waiting on exactly level k's
    # write tickets. Manifest mutation therefore never leaves this
    # thread, payload-before-seal stays absolute, and a death mid-queue
    # leaves unsealed strays resume already ignores (chaos: the
    # store.writebehind fault point). Multi-process runs seal eagerly —
    # the post-write barrier is a collective and cannot be deferred.

    def _run_seal(self, tickets, seal_fn) -> None:
        t0 = time.perf_counter()
        for t in tickets:
            self._count_ckpt_bytes(t.result())
        waited = time.perf_counter() - t0
        if waited > 1e-6:
            self.store._note_wait(waited)
        seal_fn()

    def _seal_after_writes(self, tickets, seal_fn) -> None:
        """Schedule one artifact-set seal after its payload writes."""
        if self.num_processes > 1 or not self.store.writebehind:
            self._run_seal(tickets, seal_fn)
            return
        self._pending_seals.append((tickets, seal_fn))
        # Depth 2 = one level's artifacts (edges + frontier, or one
        # level seal) in flight: flushing the OLDER level here is what
        # buys a full level of compute to overlap its writes.
        while len(self._pending_seals) > 2:
            self._run_seal(*self._pending_seals.pop(0))

    def _flush_seals(self) -> None:
        """Run every deferred seal (phase boundaries, solve end, and
        before any manifest read that must see them)."""
        while self._pending_seals:
            self._run_seal(*self._pending_seals.pop(0))

    def store_stats(self) -> dict:
        """This solve's block-store I/O deltas (the store is process-
        wide): io_wait_secs is every second the solve thread spent
        blocked on store I/O — the sync-vs-prefetch A/B observable —
        prefetch_hit_rate is reads served by cache/in-flight prefetch,
        and writebehind_queue_depth is the peak since process start."""
        now = self.store.stats()
        t0 = self._store_t0
        hits = now["prefetch_hits"] - t0["prefetch_hits"]
        misses = now["prefetch_misses"] - t0["prefetch_misses"]
        return {
            "io_wait_secs": now["io_wait_secs"] - t0["io_wait_secs"],
            "prefetch_hits": hits,
            "prefetch_misses": misses,
            "prefetch_hit_rate": (
                hits / (hits + misses) if hits + misses else 0.0
            ),
            "writebehind_writes": (
                now["writebehind_writes"] - t0["writebehind_writes"]
            ),
            "writebehind_queue_depth": now["writebehind_queue_depth_peak"],
        }

    @staticmethod
    def _rows_of(arr, s: int):
        """One shard's row of a [S, W] device array or _HostSpill (None
        when shard s is not addressable in this process)."""
        if isinstance(arr, _HostSpill):
            for _, index, rows in arr.shards:
                if (index[0].start or 0) == s:
                    return rows[0]
            return None
        for sh in arr.addressable_shards:
            if ShardedSolver._shard_id(sh) == s:
                return np.asarray(sh.data)[0]
        return None

    def _ckpt_edges_level(self, k: int, rec) -> None:
        """Persist one level's edge arrays as per-(level, shard) npz files.

        Saved the moment forward computes them — so a death between
        forward and backward resumes straight into the edge-cached
        backward instead of paying the lookup join for every level (the
        "host-spilled alongside the per-(level, shard) checkpoint npz
        files" leg of the edge design). Same multi-host write discipline
        as every other sharded artifact: each process writes only its
        addressable shards, process 0 seals post-barrier, and the seal
        records the geometry (shards, ecap, slot_len) resume validates.
        """
        if self.checkpointer is None:
            return
        tickets: List = []
        for s in range(self.S):
            e = self._rows_of(rec.eidx, s)
            sl = self._rows_of(rec.slot, s)
            if e is not None and sl is not None:
                self._count_ckpt_bytes(
                    self.checkpointer.save_edges_shard(k, s, e, sl),
                    tickets,
                )
        slot_len = (rec.slot.cap if isinstance(rec.slot, _HostSpill)
                    else rec.slot.shape[1])
        ecap = rec.ecap

        def _seal(k=k, slot_len=int(slot_len), ecap=ecap):
            self._sync_processes(f"edges_level_{k}_shards_written")
            if jax.process_index() == 0:
                self.checkpointer.finish_edges_level(
                    k, self.S, ecap, slot_len,
                    ranks=self._shard_ranks(), drain=False,
                )

        self._seal_after_writes(tickets, _seal)

    # ------------------------------------------------------------------ solve

    def solve(self) -> SolveResult:
        """Public entry: the solve body under the env-gated watchdog
        (GAMESMAN_WATCHDOG_SECS — same stall-abort contract as the
        single-device engine; `progress` is replaced atomically at each
        phase/level boundary)."""
        wd = maybe_watchdog(lambda: self.progress, logger=self.logger)
        self.status_tracker.begin(
            game=self.game.name, engine="sharded", shards=self.S,
            world=self.num_processes, rank=self.rank,
        )
        self._status_server = maybe_status_server(
            self._status_payload, rank=self.rank,
            world=self.num_processes,
        )
        if self._status_server is not None and self.coord is not None:
            # Publish this rank's /status address into the coordinator's
            # address book so rank 0's fleet view can scrape it.
            try:
                self.coord.announce(self._status_server.address)
            except CoordinationError:
                pass  # status stays rank-local; the solve is unaffected
        prev_sink = set_dispatch_sink(self._on_dispatch)
        try:
            return self._solve_impl()
        finally:
            set_dispatch_sink(prev_sink)
            if self._status_server is not None:
                self._status_server.stop()
                self._status_server = None
            # Pending pipelined seals are safe to run even on the error
            # path — their payload writes are already queued and waited
            # on — and losing them would unseal levels whose files are
            # intact. Never mask the primary failure with a seal error.
            try:
                self._flush_seals()
            except Exception:  # noqa: BLE001 - secondary failure only
                pass
            if wd is not None:
                wd.stop()
            if self.coord is not None:
                self.coord.close()

    def _status_payload(self) -> dict:
        """The /status body (HTTP handler threads; reads only
        atomically-replaced state). Rank 0 of a multi-process run folds
        in the fleet-merged view: every announced peer's /status is
        scraped (short deadline, dead peers degrade to absent) and
        per-level walls merge as max-across-ranks with stragglers
        flagged past GAMESMAN_STATUS_STRAGGLER_FACTOR x the median."""
        snap = self.status_tracker.snapshot(progress=self.progress)
        snap["retries"] = self.retries
        snap["dispatches_total"] = self.dispatch_total
        try:
            snap["io"] = {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in self.store_stats().items()
            }
        except Exception:  # noqa: BLE001 - stubbed stores in tests
            pass
        if self.rank == 0 and self.num_processes > 1:
            peer_snaps = {0: snap}
            if self.coord is not None:
                try:
                    book = self.coord.peers()
                except CoordinationError:
                    book = {}
                for r, addr in book.items():
                    if r == 0:
                        continue
                    got = obs_status.fetch_status(addr)
                    if got is not None:
                        peer_snaps[r] = got
            snap["fleet"] = obs_status.merge_fleet(
                peer_snaps, world=self.num_processes
            )
        return snap

    def _solve_impl(self) -> SolveResult:
        g = self.game
        t0 = time.perf_counter()
        init, start_level = canonical_scalar(g, g.initial_state())
        if self.checkpointer is not None:
            self.checkpointer.bind_game(g.name)
            # Elastic-resume gate (ISSUE 13): compare the sealed
            # geometry against this run's ONCE, up front — a mismatch
            # either becomes an explicit reshard adoption (logged, the
            # loaders re-partition on read) or, with GAMESMAN_RESHARD=0,
            # a loud error naming both geometries (never an opaque
            # abort, never a silent forward re-run). Stubbed
            # checkpointers in tests may not expose the check.
            check_geom = getattr(
                self.checkpointer, "check_resume_geometry", None
            )
            if check_geom is not None:
                try:
                    geom = check_geom(self.S, self.num_processes)
                except CheckpointGeometryError as e:
                    raise SolverError(str(e)) from e
                if geom["status"] == "reshard":
                    sealed = geom["sealed"]
                    self.resharded_from = (
                        sealed.get("num_shards")
                        or (sealed["shard_counts"] or [None])[-1]
                    )
                    if self.logger is not None:
                        self.logger.log({
                            "phase": "reshard",
                            "from_shards": sealed["shard_counts"],
                            "from_world": sealed.get("num_processes"),
                            "to_shards": self.S,
                            "to_world": self.num_processes,
                            "epoch": sealed.get("epoch"),
                        })
            if self.coord is not None:
                # Rank-consistent resume: every rank independently reads
                # the manifest and digests its resume state (deepest
                # mutually-sealed level + the sealed sets). Identical
                # digests meet at one epoch and pass; ANY divergence —
                # a rank seeing a different checkpoint directory or a
                # half-synced filesystem — lands on different epochs,
                # which the barrier deadline turns into a coordinated
                # abort instead of a silently-forking solve.
                digest = self.checkpointer.resume_digest(self.S)
                self.coord.barrier(f"resume:{digest}")
            if self.rank == 0:
                # Stamp the run AFTER the agreement (the stamp mutates
                # the manifest the digest reads): seals taken this run
                # carry this epoch + the rank that owns each shard.
                self.checkpointer.stamp_run(
                    self.num_processes, self._shard_ranks()
                )
            if self.coord is not None:
                self.coord.barrier("run_stamped")
        saved_shards = (
            self.checkpointer.load_frontier_shards(self.S)
            if self.checkpointer is not None
            else None
        )
        saved = None
        if saved_shards is None and self.checkpointer is not None:
            saved = self.checkpointer.load_frontiers()
        if saved_shards is not None:
            # Per-shard snapshot at a matching shard count: shard-to-shard
            # resume, no global assembly or repartition.
            levels = {}
            for k, arrs in saved_shards.items():
                shards = [np.asarray(a, dtype=g.state_dtype) for a in arrs]
                levels[k] = _SLevel(
                    np.array([a.shape[0] for a in shards], dtype=np.int64),
                    None,
                    shards,
                )
        elif saved is not None:
            levels = {}
            for k, v in saved.items():
                shards = self._repartition(np.asarray(v, dtype=g.state_dtype))
                levels[k] = _SLevel(
                    np.array([a.shape[0] for a in shards], dtype=np.int64),
                    None,
                    shards,
                )
        elif self.fast:
            # A previous run's interrupted forward left sealed per-level
            # shard files at this shard count: continue from its deepest.
            partial = (
                self.checkpointer.load_forward_level_shards(self.S)
                if self.checkpointer is not None
                else {}
            )
            levels = self._forward_fast(init, start_level,
                                        resume=partial or None)
        else:
            levels = self._forward_generic(init, start_level)
        if (saved is None and saved_shards is None
                and self.checkpointer is not None):
            self._checkpoint_frontier_shards(levels)
            self._sync_processes("forward_level_files_superseded")
            if jax.process_index() == 0:
                # The consolidated snapshot is sealed; the incremental
                # per-level files are now a redundant second copy of the
                # biggest artifact on disk.
                self.checkpointer.drop_forward_level_shards()
        t_forward = time.perf_counter() - t0
        # Positions counted from the per-shard counters, not the tables —
        # valid in store_tables=False mode too.
        num_positions = sum(int(rec.counts.sum()) for rec in levels.values())
        # The level schedule is fixed: /status's ETA model now knows the
        # remaining backward work exactly (obs/status.py).
        self.status_tracker.set_schedule(
            {k: int(rec.counts.sum()) for k, rec in levels.items()}
        )
        resolved = self._backward(levels, start_level, init)
        # Settle the tail of the pipeline before accounting: deferred
        # seals run, their tickets resolve into ckpt_bytes_*, and the
        # store deltas below include every write this solve issued.
        self._flush_seals()
        if self.checkpointer is not None:
            try:
                # Refresh the gamesman_ckpt_bytes{kind} disk gauges with
                # everything this solve left on disk (the campaign's
                # disk monitor reads the same accounting between
                # attempts).
                self.checkpointer.disk_usage()
            except (OSError, AttributeError):
                pass  # stubbed checkpointers / racing cleanup
        t_total = time.perf_counter() - t0
        root_value, root_rem = self._root_answer
        stats = {
            "game": g.name,
            "engine": "sharded",
            "shards": self.S,
            "positions": num_positions,
            "levels": len(levels),
            "retries": self.retries,
            "spill_retries": self.spill_retries,
            "backward": self.backward_mode,
            "backward_edges_levels": self.backward_edges_levels,
            "resharded_from": self.resharded_from,
            "edges_geometry_fallback_levels":
                self.edges_geometry_fallback_levels,
            "edges_bytes_spilled": self.edges_bytes_spilled,
            "edges_bytes_disk": self.edges_bytes_disk,
            "ckpt_bytes_raw": self.ckpt_bytes_raw,
            "ckpt_bytes_stored": self.ckpt_bytes_stored,
            "secs_forward": t_forward,
            "secs_backward": t_total - t_forward,
            "secs_total": t_total,
            "positions_per_sec": num_positions / max(t_total, 1e-9),
            "bytes_routed": self.bytes_routed,
            "bytes_sorted": self.bytes_sorted,
            "bytes_gathered": self.bytes_gathered,
            # ISSUE 14 dispatch economy (see engine stats of the same
            # names): proves the fused kernels dispatch less per level.
            "dispatches_total": self.dispatch_total,
            "dispatches_per_level": round(
                self.dispatch_total / max(len(levels), 1), 2),
            "fused": fused_enabled(),
            # ISSUE 15 roofline rollup (engine.roofline_stats): HBM
            # operand bytes are the sort+gather sides (routed bytes are
            # ICI traffic, accounted separately); bytes_host approximates
            # the host side from the spill + checkpoint payloads.
            # chips = shards only on REAL accelerator meshes: a faked
            # CPU mesh (tests, CPU benches) is one physical chip, and
            # dividing by S there would make this field disagree 8x
            # with bench.py's identically-named record field.
            "bytes_host": self.edges_bytes_spilled + self.ckpt_bytes_raw,
            "roofline": roofline_stats(
                self.bytes_sorted + self.bytes_gathered,
                num_positions, t_total, self.dispatch_total,
                chips=(self.S if jax.devices()[0].platform != "cpu"
                       else 1),
            ),
            **self.store_stats(),
        }
        self.progress = {"phase": "done", "rank": self.rank}
        if self.logger is not None:
            self.logger.log({"phase": "done", **stats})
        return SolveResult(g, root_value, root_rem, resolved, stats)
