"""parallel: the multi-device (hash-partitioned) solver.

TPU-native rebuild of the reference's distributed layer (SURVEY.md §2.4):
the one real parallelism strategy — hash-partitioned state-space SPMD — is
re-expressed as a 1-D jax.sharding.Mesh, with the reference's point-to-point
owner routing (`comm.send(dest=hash(pos) % world_size)`) replaced by one
jax.lax.all_to_all bucket shuffle per BFS level inside shard_map, and the
per-rank memo dicts replaced by sharded sorted-array tables.
"""

from gamesmanmpi_tpu.parallel.mesh import make_mesh
from gamesmanmpi_tpu.parallel.sharded import ShardedSolver

__all__ = ["make_mesh", "ShardedSolver"]
