"""Mesh construction: the rebuild of MPI.COMM_WORLD bring-up.

The reference gets (rank, size) from mpi4py at launch (SURVEY.md §3.1); here
the "world" is a 1-D device mesh. Multi-host bring-up is
jax.distributed.initialize over DCN (SURVEY.md §5.8 control plane) before
building the mesh over all addressable devices; single-host is just the local
devices. The solver only sees the Mesh.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

AXIS = "shards"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool | None = None):
    """Version-portable shard_map.

    jax >= 0.5 exposes jax.shard_map with a `check_vma` knob; on the 0.4.x
    line (this container ships 0.4.37) the API lives at
    jax.experimental.shard_map.shard_map and the same knob is spelled
    `check_rep`. Every sharded kernel builder routes through here so the
    engine runs on both — without this the whole sharded engine failed at
    build time with AttributeError on 0.4.x.
    """
    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _sm

    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def make_mesh(num_shards: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over `num_shards` devices (default: all available)."""
    if devices is None:
        devices = jax.devices()
    if num_shards is None:
        num_shards = len(devices)
    if num_shards > len(devices):
        raise ValueError(
            f"requested {num_shards} shards but only {len(devices)} devices"
        )
    return Mesh(np.array(devices[:num_shards]), (AXIS,))


def init_distributed(**kwargs) -> None:
    """Multi-host process-group bring-up (DCN): jax.distributed.initialize.

    No-op convenience wrapper so launchers can call it unconditionally;
    kwargs pass through (coordinator_address, num_processes, process_id).
    """
    jax.distributed.initialize(**kwargs)
