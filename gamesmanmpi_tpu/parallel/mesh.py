"""Mesh construction: the rebuild of MPI.COMM_WORLD bring-up.

The reference gets (rank, size) from mpi4py at launch (SURVEY.md §3.1); here
the "world" is a 1-D device mesh. Multi-host bring-up is
jax.distributed.initialize over DCN (SURVEY.md §5.8 control plane) before
building the mesh over all addressable devices; single-host is just the local
devices. The solver only sees the Mesh.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

from gamesmanmpi_tpu.utils.env import env_int, env_opt, env_str

AXIS = "shards"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool | None = None):
    """Version-portable shard_map.

    jax >= 0.5 exposes jax.shard_map with a `check_vma` knob; on the 0.4.x
    line (this container ships 0.4.37) the API lives at
    jax.experimental.shard_map.shard_map and the same knob is spelled
    `check_rep`. Every sharded kernel builder routes through here so the
    engine runs on both — without this the whole sharded engine failed at
    build time with AttributeError on 0.4.x.
    """
    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _sm

    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def make_mesh(num_shards: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over `num_shards` devices (default: all available)."""
    if devices is None:
        devices = jax.devices()
    if num_shards is None:
        num_shards = len(devices)
    if num_shards > len(devices):
        raise ValueError(
            f"requested {num_shards} shards but only {len(devices)} devices"
        )
    return Mesh(np.array(devices[:num_shards]), (AXIS,))


def enable_cpu_collectives() -> None:
    """Turn on cross-process CPU collectives (Gloo) before backend init.

    XLA's CPU client ships a Gloo TCP collectives implementation but
    leaves it OFF by default — a multi-process CPU run without it fails
    at the first cross-process computation with "Multiprocess
    computations aren't implemented on the CPU backend", which is
    exactly why tests/test_multihost.py used to skip on this container.
    GAMESMAN_CPU_COLLECTIVES picks the implementation (gloo/mpi/none;
    default gloo); jax versions without the knob are left untouched (a
    real TPU/GPU backend never consults it).
    """
    impl = env_str("GAMESMAN_CPU_COLLECTIVES", "gloo")
    if impl == "none":
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", impl)
    except (AttributeError, ValueError):  # jax without the knob
        pass


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, **kwargs) -> None:
    """Multi-host process-group bring-up (DCN): jax.distributed.initialize.

    Convenience wrapper so launchers can call it unconditionally; the
    identity triple falls back to the environment
    (``GAMESMAN_COORDINATOR``, ``GAMESMAN_NUM_PROCESSES``,
    ``GAMESMAN_PROCESS_ID``) so a process launcher — tools/
    launch_multihost.py — can configure children without touching their
    argv. Must run before the first backend touch; CPU collectives
    (Gloo) are enabled here for the same reason.
    """
    if coordinator_address is None:
        coordinator_address = env_opt("GAMESMAN_COORDINATOR")
    if num_processes is None:
        num_processes = env_int("GAMESMAN_NUM_PROCESSES", 1)
    if process_id is None:
        process_id = env_int("GAMESMAN_PROCESS_ID", 0)
    enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
