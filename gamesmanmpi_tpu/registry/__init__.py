"""DB registry: trustworthy distribution of solved-position databases.

The layer ABOVE one serving node (ISSUE 19): a registry server
publishes immutable DB epochs as a sha256-sealed catalog
(registry/server.py), replica nodes pull them with resumable ranged
fetches into a quarantine staging dir and verify every byte before an
atomic install + admission-gated rolling reload (registry/pull.py), and
a query for a game nobody has solved yet becomes a durable job a
campaign runner drives to a published DB (registry/jobs.py).

Distribution is where correctness goes to die: a torn download, a
half-installed replica, or a crashed publisher must always degrade to
"the fleet keeps serving the old epoch", never to a wrong answer. Every
failure shape here has a named fault point (resilience/faults.py
``registry.*`` / ``jobs.claim``) and a chaos test
(tests/test_resilience.py).
"""

from gamesmanmpi_tpu.registry.jobs import JobQueue, QueueRefused, run_pending
from gamesmanmpi_tpu.registry.pull import PullError, pull_db, sync_fleet
from gamesmanmpi_tpu.registry.server import (
    RegistryServer,
    catalog_seal,
    load_catalog,
    publish_db,
)

__all__ = [
    "JobQueue",
    "PullError",
    "QueueRefused",
    "RegistryServer",
    "catalog_seal",
    "load_catalog",
    "publish_db",
    "pull_db",
    "run_pending",
    "sync_fleet",
]
