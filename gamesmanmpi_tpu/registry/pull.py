"""Crash-safe replica pull: registry DB -> verified local install.

The trust contract (ISSUE 19): a replica NEVER serves bytes it has not
proved. Every pull stages into a quarantine directory, resumes
interrupted transfers with ranged fetches, verifies every file's
sha256 + crc32 against the registry manifest BEFORE install, and only
then atomically renames the staged directory into place and runs the
same admission gate a serving worker runs
(``db.check.verify_for_serving``). Each failure shape has one degrade
path:

* transient transport errors (5xx, connection reset/refused) — bounded
  exponential retry through ``resilience/retry.py`` (the fetch resumes
  from the bytes already staged, not from zero);
* checksum mismatch — FATAL for that copy of the bytes: the staged file
  is quarantined as ``*.corrupt`` and ONLY the bad file is re-fetched
  fresh; a second mismatch aborts the pull (the registry itself is
  serving rot);
* death mid-pull (kill/torn at the ``registry.fetch`` point) — the
  staging dir survives; the next pull resumes ranged from the verified
  prefix;
* death mid-install (``registry.install``) — the rename never happened;
  the fleet keeps serving the old epoch, the re-pull finds every staged
  byte already verified;
* failed admission gate — the installed directory is quarantined
  ``*.corrupt`` and the fleet manifest is untouched: the fleet keeps
  serving the old epoch.

``sync_fleet`` is the operator loop: pull every routed DB, rewrite the
fleet manifest (tmp+replace, validated by ``load_fleet_manifest``
first — a half-landed dir fails validation *before* any worker is
touched), drive the supervisor's rolling ``POST /reload``, and report
sync state to its ``POST /registry-sync`` so fleet ``/status`` shows
what epoch the replica believes it is on.
"""

from __future__ import annotations

import json
import os
import pathlib
import time
import urllib.error
import urllib.request

from gamesmanmpi_tpu.db.check import verify_for_serving
from gamesmanmpi_tpu.db.format import DbFormatError, MANIFEST_NAME, file_sha256
from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.registry.server import _file_crc32, catalog_seal
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.resilience.retry import retry_call
from gamesmanmpi_tpu.utils.env import env_float


class PullError(RuntimeError):
    """A pull failed for a non-transient reason (rot, bad registry)."""


def _timeout(timeout):
    return (
        env_float("GAMESMAN_REGISTRY_TIMEOUT_SECS", 30.0)
        if timeout is None else float(timeout)
    )


def _reclassify(e: urllib.error.HTTPError, url: str):
    """HTTP status -> the retry layer's transient/fatal vocabulary."""
    if e.code >= 500 or e.code == 429:
        # The retry classifier keys on message markers; "unavailable"
        # is the transport-hiccup word (resilience/retry.py).
        return RuntimeError(f"registry unavailable (HTTP {e.code}): {url}")
    return PullError(f"registry refused {url}: HTTP {e.code}")


def _get_json(url: str, timeout: float) -> dict:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        raise _reclassify(e, url) from None


def fetch_catalog(registry_url: str, timeout=None, attempts=None,
                  registry=None) -> dict:
    """GET /catalog + seal verification: refuse a catalog whose ``seal``
    disagrees with the ``dbs`` object actually parsed."""
    timeout = _timeout(timeout)
    doc = retry_call(
        lambda: _get_json(f"{registry_url.rstrip('/')}/catalog", timeout),
        point="registry.fetch", attempts=attempts, registry=registry,
    )
    if doc.get("seal") != catalog_seal(doc.get("dbs", {})):
        raise PullError(
            f"{registry_url}: catalog seal mismatch — refusing to pull "
            "from an unverifiable catalog"
        )
    return doc


# Every staged byte is sha256/crc32-verified against the registry
# manifest before the atomic rename-install (pull_db), so a torn write
# here is caught, quarantined, and re-fetched — never installed.
# sealed-write: quarantine staging download, verified before install
def _fetch_ranged(url: str, tmp_path: pathlib.Path, expect_size: int,
                  timeout: float, registry) -> int:
    """One resumable transfer attempt: append from the staged offset.

    Returns bytes fetched this attempt. Raises the retry layer's
    transient/fatal vocabulary on transport errors.
    """
    have = tmp_path.stat().st_size if tmp_path.exists() else 0
    if have > expect_size:
        tmp_path.unlink()  # over-long stray: restart clean
        have = 0
    fetched = 0
    if have < expect_size:
        req = urllib.request.Request(url)
        if have:
            req.add_header("Range", f"bytes={have}-")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                if have and resp.status != 206:
                    # Server ignored the range — restart from zero.
                    tmp_path.unlink()
                    have = 0
                mode = "ab" if have else "wb"
                with open(tmp_path, mode) as fh:
                    while True:
                        block = resp.read(1 << 20)
                        if not block:
                            break
                        fh.write(block)
                        fetched += len(block)
        except urllib.error.HTTPError as e:
            raise _reclassify(e, url) from None
    if registry is not None and fetched:
        registry.counter(
            "gamesman_registry_fetch_bytes_total",
            "payload bytes fetched from the registry",
        ).inc(fetched)
    # The chaos seam: bytes are staged but unverified. torn truncates
    # the staged file and dies — the next pull's verify catches it.
    faults.fire("registry.fetch", path=str(tmp_path), file=tmp_path.name)
    return fetched


def _digests_ok(path: pathlib.Path, rec: dict) -> bool:
    if not path.exists() or path.stat().st_size != int(rec["size"]):
        return False
    if _file_crc32(path) != int(rec["crc32"]):
        return False
    return file_sha256(path) == rec["sha256"]


def _pull_file(blob_url: str, rec: dict, tmp_dir: pathlib.Path, *,
               timeout: float, attempts, registry, stats: dict) -> None:
    """Fetch + verify ONE file into the staging dir (resume, retry,
    quarantine-and-refetch on mismatch; second mismatch is fatal)."""
    tmp_path = tmp_dir / rec["name"]
    if _digests_ok(tmp_path, rec):
        stats["resumed_files"] += 1
        return  # fully staged and verified by a previous attempt
    for trial in (1, 2):
        retry_call(
            lambda: _fetch_ranged(
                blob_url, tmp_path, int(rec["size"]), timeout, registry
            ),
            point="registry.fetch", attempts=attempts, registry=registry,
        )
        if _digests_ok(tmp_path, rec):
            return
        # Checksum mismatch is FATAL for these bytes: quarantine the
        # staged copy and re-fetch this one file from scratch.
        registry.counter(
            "gamesman_registry_corrupt_files_total",
            "staged files that failed checksum verification",
        ).inc()
        quarantine = tmp_dir / f"{rec['name']}.corrupt"
        if quarantine.exists():
            quarantine.unlink()
        if tmp_path.exists():
            os.replace(tmp_path, quarantine)
        if trial == 1:
            stats["refetched_files"] += 1
    raise PullError(
        f"{rec['name']}: checksum mismatch twice (quarantined as "
        f"{rec['name']}.corrupt) — the registry is serving rot"
    )


def pull_db(registry_url: str, name: str, dest_root, *, timeout=None,
            attempts=None, registry=None, log=None) -> dict:
    """Pull DB ``name`` into ``dest_root/<name>@<epoch12>`` (see module
    docstring for the failure contract). Idempotent: an already
    installed, manifest-sha-verified epoch returns immediately; a
    damaged install is quarantined and re-pulled.

    -> {"name", "epoch", "db", "installed", "resumed_files",
        "refetched_files", "secs"}
    """
    t0 = time.monotonic()
    timeout = _timeout(timeout)
    reg = registry or default_registry()
    base = registry_url.rstrip("/")
    dest_root = pathlib.Path(dest_root)
    man = retry_call(
        lambda: _get_json(f"{base}/db/{name}/manifest", timeout),
        point="registry.fetch", attempts=attempts, registry=reg,
    )
    epoch = man["epoch"]
    final = dest_root / f"{name}@{epoch[:12]}"
    record = {
        "name": name, "epoch": epoch, "db": str(final),
        "installed": False, "resumed_files": 0, "refetched_files": 0,
    }

    def _done(result: str) -> dict:
        reg.counter(
            "gamesman_registry_pulls_total",
            "replica pulls by outcome", result=result,
        ).inc()
        record["secs"] = round(time.monotonic() - t0, 3)
        if log is not None:
            log({"phase": "registry_pull", "result": result, **record})
        return record

    if final.is_dir():
        manifest_path = final / MANIFEST_NAME
        if manifest_path.is_file() and file_sha256(manifest_path) == epoch:
            return _done("already_installed")
        # A directory squatting on the install name that is NOT the
        # sealed epoch: quarantine it and pull fresh.
        corrupt = pathlib.Path(f"{final}.corrupt")
        if corrupt.exists():
            import shutil
            shutil.rmtree(corrupt)
        os.replace(final, corrupt)
    tmp_dir = dest_root / ".registry_tmp" / f"{name}@{epoch[:12]}"
    tmp_dir.mkdir(parents=True, exist_ok=True)
    stats = {"resumed_files": 0, "refetched_files": 0}
    try:
        for rec in man["files"]:
            _pull_file(
                f"{base}/db/{name}/blob/{rec['name']}", rec, tmp_dir,
                timeout=timeout, attempts=attempts, registry=reg,
                stats=stats,
            )
    except PullError:
        _done("corrupt")
        raise
    record.update(stats)
    for stray in tmp_dir.glob("*.corrupt"):
        stray.unlink()  # quarantined copies were re-fetched clean
    # The chaos seam: every byte verified, nothing installed yet. A
    # kill here leaves only the staging dir; the re-pull finds it.
    faults.fire("registry.install", name=name, epoch=epoch[:12])
    os.replace(tmp_dir, final)
    record["installed"] = True
    # Admission gate — the same check a serving worker warm start runs.
    # A failed gate quarantines the install; the caller's fleet keeps
    # serving whatever it was serving.
    try:
        if file_sha256(final / MANIFEST_NAME) != epoch:
            raise DbFormatError(
                f"{final}: installed manifest sha != catalog epoch"
            )
        verify_for_serving(final)
    except DbFormatError as e:
        corrupt = pathlib.Path(f"{final}.corrupt")
        if corrupt.exists():
            import shutil
            shutil.rmtree(corrupt)
        os.replace(final, corrupt)
        record["installed"] = False
        _done("quarantined")
        raise PullError(
            f"{name}@{epoch[:12]}: admission gate failed, install "
            f"quarantined: {e}"
        ) from e
    reg.counter(
        "gamesman_registry_installs_total",
        "verified replica installs",
    ).inc()
    return _done("ok")


def _post_json(url: str, payload: dict, timeout: float) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        raise _reclassify(e, url) from None


def ensure_db(registry_url: str, name: str, spec: str | None = None, *,
              dest_root=None, timeout=None, attempts=None, registry=None,
              log=None) -> dict:
    """GET the DB's registry manifest; a 404 with a ``spec`` in hand
    becomes a solve-on-demand enqueue instead of a failure.

    -> {"status": "available", **manifest} (the DB is also pulled into
    ``dest_root`` when one is given — the result rides along as
    ``"pulled"``) or {"status": "queued"/"pending"/"running", **job
    record} — the caller polls until "available"."""
    timeout = _timeout(timeout)
    base = registry_url.rstrip("/")
    try:
        man = _get_json(f"{base}/db/{name}/manifest", timeout)
    except PullError:
        if not spec:
            raise
        job = _post_json(
            f"{base}/solve", {"name": name, "spec": spec}, timeout
        )
        return {"status": job.get("state", "queued"), **job}
    out = {"status": "available", **man}
    if dest_root is not None:
        out["pulled"] = pull_db(
            registry_url, name, dest_root, timeout=timeout,
            attempts=attempts, registry=registry, log=log,
        )
    return out


def sync_fleet(registry_url: str, names: list, fleet_manifest, dest_root,
               *, control_url: str | None = None, timeout=None,
               attempts=None, registry=None, log=None) -> dict:
    """Pull every DB in ``names``, land the fleet manifest atomically,
    and drive the supervisor's rolling reload (see module docstring).

    The new manifest is validated with ``load_fleet_manifest`` BEFORE it
    replaces the live one — a half-landed install fails validation and
    the old manifest (old epoch) stays in place. Reload + sync-state
    reporting are best-effort against ``control_url`` (the supervisor's
    control endpoint); without one, the caller owns the reload.
    """
    from gamesmanmpi_tpu.serve.manifest import load_fleet_manifest

    timeout = _timeout(timeout)
    fleet_manifest = pathlib.Path(fleet_manifest)
    pulled, failed = [], []
    for name in names:
        try:
            pulled.append(
                pull_db(registry_url, name, dest_root, timeout=timeout,
                        attempts=attempts, registry=registry, log=log)
            )
        except (PullError, OSError, RuntimeError, KeyError) as e:
            failed.append({"name": name, "error": str(e)})
    result = {
        "pulled": pulled, "failed": failed, "rolled": False,
        "manifest": str(fleet_manifest),
    }
    if not pulled:
        result["status"] = "nothing_pulled"
        _report_sync(control_url, result, timeout)
        return result
    games = {}
    if fleet_manifest.exists():
        try:
            for rec in json.loads(fleet_manifest.read_text())["games"]:
                games[rec["name"]] = rec
        except (ValueError, KeyError, OSError):
            games = {}  # junk manifest: rebuild from the pulls alone
    for rec in pulled:
        games[rec["name"]] = {"name": rec["name"], "db": rec["db"]}
    doc = {"version": 1, "games": sorted(games.values(),
                                         key=lambda r: r["name"])}
    tmp = fleet_manifest.with_name(
        f"{fleet_manifest.name}.{os.getpid()}.tmp"
    )
    tmp.write_text(json.dumps(doc, indent=1))
    try:
        load_fleet_manifest(tmp)  # fail BEFORE any worker is touched
    except ValueError as e:
        tmp.unlink()
        result["status"] = "manifest_rejected"
        result["error"] = str(e)
        _report_sync(control_url, result, timeout)
        raise PullError(
            f"pulled manifest failed validation, fleet untouched: {e}"
        ) from e
    os.replace(tmp, fleet_manifest)
    result["status"] = "manifest_landed"
    if control_url:
        try:
            _post_json(f"{control_url.rstrip('/')}/reload", {}, timeout)
            result["rolled"] = True
            result["status"] = "rolled"
        except (OSError, RuntimeError, ValueError) as e:
            result["status"] = "reload_failed"
            result["error"] = str(e)
    _report_sync(control_url, result, timeout)
    return result


def _report_sync(control_url: str | None, result: dict,
                 timeout: float) -> None:
    """Best-effort sync-state report to the supervisor's control
    endpoint (shows up in fleet /status as ``registry_sync``)."""
    if not control_url:
        return
    payload = {
        "status": result.get("status"),
        "epochs": {p["name"]: p["epoch"][:12] for p in result["pulled"]},
        "failed": [f["name"] for f in result["failed"]],
        "wall_time": time.time(),
    }
    try:
        _post_json(
            f"{control_url.rstrip('/')}/registry-sync", payload, timeout
        )
    except (OSError, RuntimeError, ValueError):
        pass  # status reporting must never fail a sync
