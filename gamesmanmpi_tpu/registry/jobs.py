"""Solve-on-demand: a durable job queue + the campaign runner.

A query for a game nobody has published yet should become a solved,
published DB without a human in the loop — the unattended-ladder
program of resilience/campaign.py, triggered by demand. The queue is an
fsync'd append-only JSONL ledger (the campaign ledger idiom): every
state transition is one durable line, state is REPLAY of the ledger, so
a runner SIGKILLed at any point — mid-claim, mid-campaign, mid-publish
— loses nothing. The next runner replays, classifies the dead claim
(pid gone / lease expired), and resumes.

Ledger ops::

    {"op": "enqueue",  "job": <id>, "spec": ..., "db_name": ...}
    {"op": "claim",    "job": <id>, "pid": ..., "lease_until": ...}
    {"op": "release",  "job": <id>, "error": ...}     back to pending
    {"op": "complete", "job": <id>, "epoch": ...}
    {"op": "fail",     "job": <id>, "error": ...}     terminal

Jobs are deduped by ``spec_hash`` (the id IS the hash of
``(db_name, spec)``): enqueueing a spec already pending/running/done
returns the existing job. Admission control refuses new work when the
queue is already ``GAMESMAN_JOBS_MAX_DEPTH`` deep or free disk under
the ledger is below ``GAMESMAN_JOBS_DISK_FLOOR_MB`` — a thundering herd
of novel queries must degrade to 429s, not fill the disk with
half-solved campaigns.

The runner (``run_pending``) drives each claimed job through the
existing unattended pipeline: ``tools/run_campaign.py`` (auto-resume
solve to a checkpoint tree) -> ``export-db --from-checkpoint`` ->
optional ``tools/build_book.py`` -> ``registry.server.publish_db``. A
step failure releases the job (retried up to
``GAMESMAN_JOBS_MAX_ATTEMPTS`` claims, then failed terminally).
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import shutil
import subprocess
import sys
import time

from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.utils.env import env_float, env_int

#: Repo root (…/gamesmanmpi_tpu/registry/jobs.py -> repo), for the
#: tools/ scripts the runner shells out to.
_REPO = pathlib.Path(__file__).resolve().parents[2]


class QueueRefused(RuntimeError):
    """Admission control said no (queue depth / disk floor)."""


def spec_hash(spec: str, db_name: str | None = None) -> str:
    """The dedup/config key: two queries for the same (name, spec) are
    one job, whatever order they arrive in."""
    blob = f"{db_name or ''}\n{spec.strip()}"
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _pid_alive(pid) -> bool:
    if not pid:
        return False
    try:
        os.kill(int(pid), 0)
    except (OSError, ValueError):
        return False
    return True


class JobQueue:
    """Durable solve-on-demand queue over one append-only ledger.

    Single-writer-per-call, multi-process safe for the intended shape
    (one registry server enqueueing, one runner claiming): every
    mutation is an fsync'd append and state is ledger replay, so a
    crash between any two lines is recoverable by construction.
    """

    def __init__(self, path, registry=None):
        self.path = pathlib.Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.registry = registry or default_registry()

    # ------------------------------------------------------------ ledger

    def _append(self, record: dict) -> None:
        line = json.dumps({"wall_time": time.time(), **record},
                          default=str)
        with open(self.path, "a") as fh:
            fh.write(line + "\n")
            fh.flush()
            os.fsync(fh.fileno())

    # wire: producer
    def _replay(self) -> dict:
        """Ledger -> {job_id: job dict}. A torn tail line (death
        mid-append) is skipped, exactly like the campaign ledger.
        Job records cross the wire verbatim (``POST /solve`` responses,
        ``/jobs`` snapshots), hence the producer annotation."""
        jobs: dict = {}
        if not self.path.exists():
            return jobs
        with open(self.path) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail — appends never tear earlier lines
                jid = rec.get("job")
                if not jid:
                    continue
                op = rec.get("op")
                if op == "enqueue":
                    jobs[jid] = {
                        "id": jid,
                        "spec": rec.get("spec"),
                        "db_name": rec.get("db_name"),
                        "state": "pending",
                        "attempts": 0,
                        "enqueue_time": rec.get("wall_time"),
                        "error": None,
                    }
                    continue
                job = jobs.get(jid)
                if job is None:
                    continue  # op for an unknown job: ignore, stay durable
                if op == "claim":
                    job["state"] = "running"
                    job["attempts"] += 1
                    job["pid"] = rec.get("pid")
                    job["lease_until"] = rec.get("lease_until")
                elif op == "release":
                    job["state"] = "pending"
                    job["error"] = rec.get("error")
                elif op == "complete":
                    job["state"] = "done"
                    job["epoch"] = rec.get("epoch")
                    job["db"] = rec.get("db")
                elif op == "fail":
                    job["state"] = "failed"
                    job["error"] = rec.get("error")
        return jobs

    # ----------------------------------------------------------- queries

    @staticmethod
    def _reclaimable(job: dict) -> bool:
        """A running job whose runner is provably gone: pid dead or
        lease expired — the classify half of classify-and-resume."""
        if job["state"] != "running":
            return False
        if not _pid_alive(job.get("pid")):
            return True
        lease = job.get("lease_until")
        return lease is not None and time.time() > float(lease)

    def jobs(self) -> dict:
        return self._replay()

    def depth(self, jobs: dict | None = None) -> int:
        jobs = self._replay() if jobs is None else jobs
        return sum(1 for j in jobs.values()
                   if j["state"] in ("pending", "running"))

    def snapshot(self) -> dict:
        jobs = self._replay()
        depth = self.depth(jobs)
        self.registry.gauge(
            "gamesman_jobs_queue_depth",
            "solve-on-demand jobs pending or running",
        ).set(depth)
        by_state: dict = {}
        for j in jobs.values():
            by_state[j["state"]] = by_state.get(j["state"], 0) + 1
        return {
            "kind": "job_queue", "depth": depth, "by_state": by_state,
            "jobs": sorted(jobs.values(), key=lambda j: j["enqueue_time"]),
        }

    # --------------------------------------------------------- mutations

    def enqueue(self, spec: str, name: str | None = None) -> dict:
        """Queue a solve (deduped, admission-controlled) -> job dict
        with a ``state`` field; raises :class:`QueueRefused` when
        admission says no and ``ValueError`` on an empty spec."""
        if not spec or not spec.strip():
            raise ValueError("empty game spec")
        jid = spec_hash(spec, name)
        jobs = self._replay()
        existing = jobs.get(jid)
        if existing is not None and existing["state"] != "failed":
            self.registry.counter(
                "gamesman_jobs_deduped_total",
                "enqueues answered by an existing job (spec_hash dedup)",
            ).inc()
            return existing
        depth = self.depth(jobs)
        max_depth = env_int("GAMESMAN_JOBS_MAX_DEPTH", 16)
        if depth >= max_depth:
            self._refused("queue depth")
            raise QueueRefused(
                f"job queue at max depth ({depth} >= {max_depth}); "
                "retry later"
            )
        floor_mb = env_float("GAMESMAN_JOBS_DISK_FLOOR_MB", 0.0)
        if floor_mb > 0:
            free_mb = shutil.disk_usage(self.path.parent).free / 1e6
            if free_mb < floor_mb:
                self._refused("disk floor")
                raise QueueRefused(
                    f"free disk {free_mb:.0f} MB under the "
                    f"{floor_mb:g} MB job floor; not queueing new solves"
                )
        self._append({"op": "enqueue", "job": jid, "spec": spec.strip(),
                      "db_name": name})
        self.registry.counter(
            "gamesman_jobs_enqueued_total", "solve-on-demand jobs queued",
        ).inc()
        self.registry.gauge(
            "gamesman_jobs_queue_depth",
            "solve-on-demand jobs pending or running",
        ).set(depth + 1)
        return self._replay()[jid]

    def _refused(self, reason: str) -> None:
        self.registry.counter(
            "gamesman_jobs_refused_total",
            "enqueues refused by admission control", reason=reason,
        ).inc()

    def claim(self, pid: int | None = None) -> dict | None:
        """Claim the oldest runnable job (pending, or a dead/expired
        claim being reclaimed) -> job dict, or None when the queue has
        nothing runnable. Jobs past ``GAMESMAN_JOBS_MAX_ATTEMPTS``
        claims are failed terminally instead of claimed again."""
        pid = os.getpid() if pid is None else int(pid)
        max_attempts = env_int("GAMESMAN_JOBS_MAX_ATTEMPTS", 3)
        lease_secs = env_float("GAMESMAN_JOBS_LEASE_SECS", 900.0)
        jobs = self._replay()
        for job in sorted(jobs.values(), key=lambda j: j["enqueue_time"]):
            resumed = self._reclaimable(job)
            if job["state"] != "pending" and not resumed:
                continue
            if job["attempts"] >= max_attempts:
                self._append({
                    "op": "fail", "job": job["id"],
                    "error": f"attempts exhausted "
                             f"({job['attempts']} >= {max_attempts})",
                })
                self.registry.counter(
                    "gamesman_jobs_failed_total",
                    "jobs failed terminally",
                ).inc()
                continue
            self._append({
                "op": "claim", "job": job["id"], "pid": pid,
                "lease_until": time.time() + lease_secs,
            })
            self.registry.counter(
                "gamesman_jobs_claimed_total", "job claims by runners",
            ).inc()
            if resumed:
                self.registry.counter(
                    "gamesman_jobs_resumed_total",
                    "dead/expired claims reclaimed by a later runner",
                ).inc()
            # The chaos seam: the claim is durable, the work has not
            # started. A kill here leaves a running job with a dead
            # pid — exactly what _reclaimable resumes.
            faults.fire("jobs.claim", job=job["id"], pid=pid)
            return self._replay()[job["id"]]
        return None

    def release(self, job_id: str, error: str | None = None) -> None:
        self._append({"op": "release", "job": job_id,
                      "error": (error or "")[:500] or None})

    def complete(self, job_id: str, **info) -> None:
        self._append({"op": "complete", "job": job_id, **info})
        self.registry.counter(
            "gamesman_jobs_completed_total",
            "jobs driven to a published DB",
        ).inc()

    def fail(self, job_id: str, error: str) -> None:
        self._append({"op": "fail", "job": job_id, "error": error[:500]})
        self.registry.counter(
            "gamesman_jobs_failed_total", "jobs failed terminally",
        ).inc()


# ------------------------------------------------------------- the runner


def _run_step(cmd: list, log, what: str, env: dict | None = None) -> None:
    """One pipeline step as a subprocess; raises RuntimeError with the
    captured output tail on a non-zero exit."""
    if log is not None:
        log({"phase": "job_step", "what": what, "cmd": cmd[:6]})
    proc = subprocess.run(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=dict(os.environ, **env) if env else None,
    )
    if proc.returncode != 0:
        tail = (proc.stdout or "")[-2000:]
        raise RuntimeError(
            f"{what} exited {proc.returncode}: …{tail}"
        )


def run_job(queue: JobQueue, job: dict, registry_root, work_dir, *,
            book_plies: int = 0, solver_args: list | None = None,
            log=None) -> dict:
    """Drive ONE claimed job through campaign -> export -> book ->
    publish. Returns {"job", "ok", ...}; a failed step releases the job
    for a later claim (attempts-capped by ``claim``)."""
    from gamesmanmpi_tpu.registry.server import publish_db

    # Absolute paths throughout: the campaign driver runs its attempt
    # subprocesses with cwd=REPO, so a relative checkpoint dir would
    # silently land inside the repo tree.
    work = pathlib.Path(work_dir).resolve() / f"job-{job['id']}"
    ckpt, db = work / "ckpt", work / "db"
    work.mkdir(parents=True, exist_ok=True)
    name = job.get("db_name") or job["spec"].split(":")[0]
    try:
        _run_step(
            [sys.executable, str(_REPO / "tools" / "run_campaign.py"),
             job["spec"], "--checkpoint-dir", str(ckpt),
             *(solver_args or [])],
            log, "run_campaign",
        )
        _run_step(
            [sys.executable, "-m", "gamesmanmpi_tpu.cli", "export-db",
             job["spec"], "--out", str(db), "--from-checkpoint",
             str(ckpt), "--overwrite"],
            log, "export-db",
        )
        if book_plies > 0:
            _run_step(
                [sys.executable, str(_REPO / "tools" / "build_book.py"),
                 str(db), "--plies", str(book_plies)],
                log, "build_book",
            )
        record = publish_db(registry_root, name, db,
                            registry=queue.registry)
    except (RuntimeError, OSError, ValueError) as e:
        queue.release(job["id"], error=str(e))
        return {"job": job["id"], "ok": False, "error": str(e)}
    queue.complete(job["id"], epoch=record["epoch"], db=name)
    return {"job": job["id"], "ok": True, "db": name,
            "epoch": record["epoch"]}


def run_pending(queue: JobQueue, registry_root, work_dir, *,
                book_plies: int = 0, solver_args: list | None = None,
                once: bool = False, log=None) -> list:
    """Claim-and-run until the queue has nothing runnable (or one job
    with ``once``). Returns the per-job result records."""
    results = []
    while True:
        job = queue.claim()
        if job is None:
            break
        results.append(
            run_job(queue, job, registry_root, work_dir,
                    book_plies=book_plies, solver_args=solver_args,
                    log=log)
        )
        if once:
            break
    return results
