"""Registry server: a sha256-sealed catalog of DB epochs over HTTP.

Stdlib ``ThreadingHTTPServer`` (the serve/server.py idiom — no
framework, one thread per connection) publishing immutable DB payloads:

    GET  /catalog               the sealed catalog: every published DB's
                                name, epoch (manifest sha256), and
                                per-file digests; ``seal`` is the sha256
                                of the canonical ``dbs`` JSON so a
                                client proves the catalog it parsed is
                                the one the publisher sealed
    GET  /db/<name>/manifest    one DB's registry record (files with
                                size + sha256 + crc32 — the pull
                                client's verification contract)
    GET  /db/<name>/blob/<file> payload bytes; honors ``Range:
                                bytes=N-[M]`` so an interrupted pull
                                resumes instead of restarting
    POST /publish               {"name": ..., "dir": ...} — install a
                                server-local DB directory as a new
                                epoch and seal the catalog update
                                atomically (write-then-seal: payload
                                lands first, ``catalog.json`` replaces
                                last, so a death in between leaves the
                                OLD catalog authoritative)
    POST /solve                 {"spec": ..., "name": ...} — enqueue a
                                solve-on-demand job (registry/jobs.py)
                                for a game nobody has published yet
    GET  /jobs                  job-queue snapshot
    GET  /healthz               liveness + catalog summary

Registry root layout::

    root/
      catalog.json              sealed catalog (atomic tmp+replace)
      dbs/<name>/<epoch12>/     one immutable payload per epoch
      jobs.jsonl                solve-on-demand ledger (when enabled)

Payload directories are immutable once the catalog names them — a
re-publish of the same epoch is a no-op, a new epoch lands beside the
old one (readers pulling the old epoch keep working mid-publish).
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import re
import shutil
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from gamesmanmpi_tpu.db.format import (
    MANIFEST_NAME,
    DbFormatError,
    file_sha256,
    read_manifest,
)
from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.resilience import faults

#: Same routing-key shape as serve/manifest.py: a name must survive a
#: URL path segment (and a directory name) un-escaped.
_NAME_RE = re.compile(r"^[A-Za-z0-9_.-]+$")

CATALOG_NAME = "catalog.json"
CATALOG_VERSION = 1

#: One ranged read per loop iteration when streaming a blob.
_BLOB_CHUNK = 1 << 20


def _file_crc32(path, chunk: int = 1 << 22) -> int:
    """Streaming crc32 (cheap second witness next to the sha256 — a
    pull client can spot a torn range without re-hashing the prefix)."""
    crc = 0
    with open(path, "rb") as fh:  # store-io: registry digests raw payload bytes
        while True:
            block = fh.read(chunk)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def catalog_seal(dbs: dict) -> str:
    """sha256 of the canonical ``dbs`` JSON — the catalog's seal.

    Canonical = sorted keys, no whitespace variance; the client recomputes
    this over the ``dbs`` object it parsed and refuses a catalog whose
    seal disagrees (a truncated or hand-edited catalog must not drive a
    pull)."""
    blob = json.dumps(dbs, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def load_catalog(root) -> dict:
    """Read the sealed catalog (empty catalog when none exists yet)."""
    path = pathlib.Path(root) / CATALOG_NAME
    if not path.exists():
        return {"version": CATALOG_VERSION, "dbs": {}, "seal": catalog_seal({})}
    doc = json.loads(path.read_text())
    if doc.get("version") != CATALOG_VERSION:
        raise ValueError(
            f"{path}: catalog version {doc.get('version')!r}, expected "
            f"{CATALOG_VERSION}"
        )
    return doc


def _catalog_doc(dbs: dict) -> dict:
    return {"version": CATALOG_VERSION, "dbs": dbs,
            "seal": catalog_seal(dbs)}


def _seal_catalog(root, dbs: dict) -> dict:
    """Atomically replace the catalog with a freshly sealed one."""
    root = pathlib.Path(root)
    doc = _catalog_doc(dbs)
    tmp = root / f"{CATALOG_NAME}.{os.getpid()}.tmp"
    tmp.write_text(json.dumps(doc, indent=1, sort_keys=True))
    os.replace(tmp, root / CATALOG_NAME)
    return doc


def publish_db(root, name: str, src_dir, registry=None) -> dict:
    """Install ``src_dir`` (a finalized export-db directory) as epoch
    ``sha256(manifest.json)`` of DB ``name`` and seal the catalog.

    Write-then-seal (GM801/GM802 discipline): the payload directory is
    copied to a tmp sibling and renamed into place FIRST; the catalog —
    the only thing readers trust — is replaced LAST. A crash between the
    two leaves an orphan payload the next publish of the same epoch
    adopts, and the old catalog stays authoritative. Publishing an epoch
    the catalog already names is a no-op (returns the existing record).

    Returns the catalog record for ``name``. Raises ``ValueError`` /
    ``DbFormatError`` on a bad name or a directory that is not a
    finalized DB.
    """
    root = pathlib.Path(root)
    src = pathlib.Path(src_dir)
    if not _NAME_RE.match(name or ""):
        raise ValueError(f"registry DB name {name!r} is not a url-safe token")
    read_manifest(src)  # refuse anything that is not a finalized DB
    epoch = file_sha256(src / MANIFEST_NAME)
    dbs = load_catalog(root)["dbs"]
    existing = dbs.get(name)
    if existing is not None and existing["epoch"] == epoch:
        return existing
    rel = f"dbs/{name}/{epoch[:12]}"
    final = root / rel
    if not final.is_dir():
        tmp_payload = root / "dbs" / name / f".tmp-{epoch[:12]}-{os.getpid()}"
        if tmp_payload.exists():
            shutil.rmtree(tmp_payload)
        tmp_payload.mkdir(parents=True)
        for entry in sorted(src.iterdir()):
            if entry.is_file():
                shutil.copyfile(entry, tmp_payload / entry.name)
        os.replace(tmp_payload, final)
    files = []
    for entry in sorted(final.iterdir()):
        if not entry.is_file():
            continue
        files.append({
            "name": entry.name,
            "size": entry.stat().st_size,
            "sha256": file_sha256(entry),
            "crc32": _file_crc32(entry),
        })
    record = {
        "epoch": epoch,
        "path": rel,
        "files": files,
        "published_time": time.time(),
    }
    # The chaos seam: payload is fully installed, the catalog still
    # names the OLD epoch. A kill here must leave a working registry.
    faults.fire("registry.publish", name=name, epoch=epoch[:12])
    dbs[name] = record
    _seal_catalog(root, dbs)
    (registry or default_registry()).counter(
        "gamesman_registry_publishes_total",
        "DB epochs published into the registry catalog",
    ).inc()
    return record


# wire: 429-retry-after
class _RegistryHandler(BaseHTTPRequestHandler):
    server_version = "gamesman-registry/1"
    protocol_version = "HTTP/1.1"
    timeout = 30

    def log_message(self, fmt, *args):
        pass

    # self.server is the _RegistryHTTPServer below.

    def _send_json(self, code: int, payload: dict, headers=None) -> None:
        body = json.dumps(payload).encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True

    def _read_body(self) -> dict | None:
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if not 0 < length <= (1 << 20):
                return None
            return json.loads(self.rfile.read(length))
        except (ValueError, OSError):
            return None

    def do_GET(self):  # noqa: N802 - http.server API
        srv = self.server.registry_server
        if self.path == "/catalog":
            self._send_json(200, load_catalog(srv.root))
        elif self.path == "/healthz":
            catalog = load_catalog(srv.root)
            self._send_json(200, {
                "status": "ok",
                "kind": "registry",
                "dbs": sorted(catalog["dbs"]),
                "jobs": srv.queue.snapshot() if srv.queue else None,
            })
        elif self.path == "/jobs":
            if srv.queue is None:
                self._send_json(404, {"error": "no job queue configured"})
            else:
                self._send_json(200, srv.queue.snapshot())
        elif self.path.startswith("/db/"):
            self._get_db(srv)
        else:
            self._send_json(404, {"error": f"no such path {self.path!r}"})

    def _get_db(self, srv) -> None:
        parts = self.path.split("/")  # ['', 'db', name, what, (file)]
        if len(parts) < 4 or not _NAME_RE.match(parts[2]):
            self._send_json(404, {"error": f"no such path {self.path!r}"})
            return
        name = parts[2]
        record = load_catalog(srv.root)["dbs"].get(name)
        if record is None:
            self._send_json(404, {
                "error": f"no such DB {name!r}",
                "solve_hint": "POST /solve {\"name\": ..., \"spec\": ...} "
                "to queue an on-demand solve" if srv.queue else None,
            })
            return
        if parts[3] == "manifest" and len(parts) == 4:
            self._send_json(200, {"name": name, **record})
        elif parts[3] == "blob" and len(parts) == 5:
            self._send_blob(srv, record, parts[4])
        else:
            self._send_json(404, {"error": f"no such path {self.path!r}"})

    def _send_blob(self, srv, record: dict, filename: str) -> None:
        # Only files the sealed record names are reachable — the record
        # is the allowlist, so traversal is impossible by construction.
        rec = next(
            (f for f in record["files"] if f["name"] == filename), None
        )
        if rec is None:
            self._send_json(404, {"error": f"no such file {filename!r}"})
            return
        path = pathlib.Path(srv.root) / record["path"] / filename
        size = rec["size"]
        start, end = 0, size
        rng = self.headers.get("Range")
        if rng and rng.startswith("bytes="):
            lo, _, hi = rng[len("bytes="):].partition("-")
            try:
                start = int(lo) if lo else 0
                end = int(hi) + 1 if hi else size
            except ValueError:
                start, end = 0, size
            if not 0 <= start <= end <= size:
                self._send_json(416, {"error": f"bad range {rng!r}"})
                return
        try:
            self.send_response(206 if (start, end) != (0, size) else 200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Accept-Ranges", "bytes")
            self.send_header("Content-Length", str(end - start))
            if (start, end) != (0, size):
                self.send_header(
                    "Content-Range", f"bytes {start}-{end - 1}/{size}"
                )
            self.end_headers()
            sent = 0
            # store-io: registry streams raw payload bytes to pull clients
            with open(path, "rb") as fh:
                fh.seek(start)
                remaining = end - start
                while remaining > 0:
                    block = fh.read(min(_BLOB_CHUNK, remaining))
                    if not block:
                        break
                    self.wfile.write(block)
                    sent += len(block)
                    remaining -= len(block)
            srv.registry.counter(
                "gamesman_registry_blob_bytes_total",
                "payload bytes streamed to pull clients",
            ).inc(sent)
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True

    def do_POST(self):  # noqa: N802 - http.server API
        srv = self.server.registry_server
        body = self._read_body()
        self.close_connection = True
        if body is None:
            self._send_json(400, {"error": "body must be a small JSON object"})
            return
        if self.path == "/publish":
            try:
                record = publish_db(
                    srv.root, str(body.get("name") or ""), body.get("dir"),
                    registry=srv.registry,
                )
            except (ValueError, DbFormatError, OSError, TypeError) as e:
                self._send_json(400, {"error": str(e)})
                return
            self._send_json(200, {"ok": True, "epoch": record["epoch"]})
        elif self.path == "/solve":
            if srv.queue is None:
                self._send_json(404, {"error": "no job queue configured"})
                return
            from gamesmanmpi_tpu.registry.jobs import QueueRefused
            try:
                job = srv.queue.enqueue(
                    str(body.get("spec") or ""),
                    name=str(body.get("name") or "") or None,
                )
            except ValueError as e:
                self._send_json(400, {"error": str(e)})
                return
            except QueueRefused as e:
                # Refusal is load shedding, not failure: tell pull
                # clients when to come back instead of letting them
                # hammer a full queue.
                self._send_json(429, {"error": str(e)},
                                headers={"Retry-After": "5"})
                return
            self._send_json(202, {"ok": True, **job})
        else:
            self._send_json(404, {"error": f"no such path {self.path!r}"})


class _RegistryHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, registry_server):
        super().__init__(addr, _RegistryHandler)
        self.registry_server = registry_server


class RegistryServer:
    """One registry root served over HTTP (see module docstring)."""

    def __init__(self, root, host: str = "127.0.0.1", port: int = 0,
                 queue=None, registry=None):
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.queue = queue
        self.registry = registry or default_registry()
        self._httpd = _RegistryHTTPServer((host, port), self)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "RegistryServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="gamesman-registry", daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
