"""Compat shim: unmodified reference-style game modules.

Two paths (see package docstring):

  solve_module(module)   — host solve via the memoized-negamax oracle. The
                           reference's own execution model (per-position
                           Python calls) at single-process scale; correct for
                           any acyclic game with hashable positions.
  TensorizedModule(...)  — lifts a scalar module onto the TensorGame protocol
                           with jax.pure_callback, so the *same jitted
                           level-synchronous engine* (and sharded solver)
                           drives an unmodified plugin. Positions must be
                           ints (they are in the reference's shipped games:
                           "position packed as int", SURVEY.md §2.2), and a
                           topological `level_fn` must exist — module
                           attribute `level_of`, or passed explicitly.
                           Deliberately slow (host round-trip per batch,
                           SURVEY.md §7) and excluded from benchmarks.
"""

from __future__ import annotations

import importlib.util
import pathlib

import jax
import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.core.bitops import SENTINEL64 as SENTINEL
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame
from gamesmanmpi_tpu.solve.oracle import (
    module_api,
    normalize_value,
    oracle_solve,
)


# Bounded BFS probe size for auto-deriving max_moves. Small games are fully
# explored (exact bound); larger games get an estimate that the grow-and-
# retry loop in solve_module_jitted corrects. Tests shrink this to force the
# retry path deterministically.
_PROBE_LIMIT = 1024


def _probe_max_moves(initial, gen, do, prim) -> int:
    """Max observed branching over a bounded BFS from the initial position."""
    seen = {int(initial)}
    frontier = [int(initial)]
    best = 1
    while frontier and len(seen) < _PROBE_LIMIT:
        nxt = []
        for pos in frontier:
            if normalize_value(prim(pos)) != UNDECIDED:
                continue
            moves = list(gen(pos))
            best = max(best, len(moves))
            for m in moves:
                child = int(do(pos, m))
                if child not in seen:
                    seen.add(child)
                    nxt.append(child)
                if len(seen) >= _PROBE_LIMIT:
                    break
        frontier = nxt
    return best


def solve_module_jitted(module, *, devices: int = 1, max_retries: int = 6,
                        **kwargs):
    """Drive an unmodified scalar module through the jitted engine.

    Lifts the module with TensorizedModule (auto-deriving max_moves when the
    module doesn't declare it) and solves; if a position mid-solve turns out
    to have more moves than the probe saw, the expand callback raises and
    this loop doubles max_moves and re-solves (each retry builds a fresh
    wrapper, so its private kernel cache is dropped with it).

    `level_of` cannot be auto-derived the same way: a topological level
    function is a *global* invariant of the game graph (every move strictly
    increases it), and no bounded sample can certify one — so modules must
    still declare it (or callers pass level_fn=).

    kwargs go to the solver (paranoid=, logger=, checkpointer=, ...).
    Returns a SolveResult.
    """
    game = TensorizedModule(module)
    for attempt in range(max_retries + 1):
        if devices > 1:
            from gamesmanmpi_tpu.parallel import ShardedSolver

            solver = ShardedSolver(game, num_shards=devices, **kwargs)
        else:
            from gamesmanmpi_tpu.solve import Solver

            solver = Solver(game, **kwargs)
        try:
            return solver.solve()
        except Exception as e:  # XlaRuntimeError wraps the callback's raise
            if (
                "GAMESMAN_MAX_MOVES_OVERFLOW" not in str(e)
                or attempt == max_retries
            ):
                raise
            game = TensorizedModule(module, max_moves=2 * game.max_moves)


def load_game_module(path):
    """Dynamic plugin import, the solver_launcher.py way (SURVEY.md §3.1):
    load a Python file, validate the 4-function API, return the module."""
    path = pathlib.Path(path)
    spec = importlib.util.spec_from_file_location(path.stem, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    module_api(module)  # validates required attributes
    return module


def solve_module(module):
    """Solve an unmodified reference-style module on host.

    Returns (value, remoteness, table) — table maps every reachable position
    to (value, remoteness), the same observable output as the reference.
    """
    return oracle_solve(module)


class TensorizedModule(TensorGame):
    """A scalar 4-function module lifted onto the batched TensorGame API."""

    _instance_counter = 0

    def __init__(
        self,
        module,
        *,
        max_moves: int | None = None,
        level_fn=None,
        max_level_jump: int | None = None,
        num_levels: int | None = None,
    ):
        initial, gen, do, prim = module_api(module)
        if not isinstance(initial, (int, np.integer)):
            raise TypeError(
                "TensorizedModule needs int-packed positions; use "
                "solve_module() for arbitrary hashable positions"
            )
        self._gen, self._do, self._prim = gen, do, prim
        self._initial = np.uint64(initial)
        self.name = f"compat_{getattr(module, '__name__', 'module')}"
        # Unlike built-in games, `name` does not encode this wrapper's full
        # identity (two modules can share a file stem; max_moves/level_fn are
        # caller-supplied), so the base cache_key contract — equal key =>
        # identical kernels — would not hold and the engine's kernel cache
        # could reuse another module's host callbacks. A per-instance token
        # plus a per-instance cache dict (engine.get_kernel honors it)
        # disables cross-instance sharing AND lets the kernels be collected
        # with this wrapper instead of living in the process-wide cache.
        TensorizedModule._instance_counter += 1
        self._cache_token = TensorizedModule._instance_counter
        self._private_kernel_cache: dict = {}
        level_fn = level_fn or getattr(module, "level_of", None)
        if level_fn is None:
            raise ValueError(
                "a topological level function is required: pass level_fn= or "
                "define level_of(pos) in the module (see games/base.py)"
            )
        self._level_fn = level_fn
        if max_moves is None:
            max_moves = getattr(module, "max_moves", None)
        if max_moves is None:
            # Auto-derive the static [B, M] kernel width by a bounded BFS
            # probe. Games whose branching grows beyond the probed sample
            # under-size it; _expand_host then raises a recognizable error
            # and solve_module_jitted grows max_moves and retries — the
            # probe-and-grow design BASELINE's "runs unmodified" asks for.
            max_moves = _probe_max_moves(self._initial, gen, do, prim)
        self.max_moves = int(max_moves)
        self.max_level_jump = int(
            max_level_jump or getattr(module, "max_level_jump", 1)
        )
        self.num_levels = int(num_levels or getattr(module, "num_levels", 1 << 20))

    @property
    def cache_key(self):
        return (type(self).__qualname__, self.name, self._cache_token)

    def initial_state(self) -> np.uint64:
        return self._initial

    # Host callbacks — one python round-trip per batch, not per position.

    def _expand_host(self, states):
        states = np.asarray(states, np.uint64)
        B = states.shape[0]
        kids = np.full((B, self.max_moves), SENTINEL, dtype=np.uint64)
        mask = np.zeros((B, self.max_moves), dtype=bool)
        for i, s in enumerate(states):
            if s == SENTINEL:
                continue
            pos = int(s)
            if normalize_value(self._prim(pos)) != UNDECIDED:
                continue
            moves = list(self._gen(pos))
            if len(moves) > self.max_moves:
                # The unique token is the retry contract with
                # solve_module_jitted (exception types don't survive the
                # callback boundary; generic words like "max_moves" could
                # collide with a game module's own error text).
                raise ValueError(
                    f"GAMESMAN_MAX_MOVES_OVERFLOW: position {pos:#x} has "
                    f"{len(moves)} moves > max_moves={self.max_moves}"
                )
            for j, m in enumerate(moves):
                kids[i, j] = self._do(pos, m)
                mask[i, j] = True
        return kids, mask

    def _primitive_host(self, states):
        states = np.asarray(states, np.uint64)
        out = np.zeros(states.shape, dtype=np.uint8)
        for i, s in enumerate(states):
            if s != SENTINEL:
                out[i] = normalize_value(self._prim(int(s)))
        return out

    def _level_host(self, states):
        states = np.asarray(states, np.uint64)
        out = np.zeros(states.shape, dtype=np.int32)
        for i, s in enumerate(states):
            if s != SENTINEL:
                out[i] = self._level_fn(int(s))
        return out

    # TensorGame protocol: pure_callback keeps the engine jittable.

    def expand(self, states):
        shape = states.shape + (self.max_moves,)
        return jax.pure_callback(
            self._expand_host,
            (
                jax.ShapeDtypeStruct(shape, jnp.uint64),
                jax.ShapeDtypeStruct(shape, jnp.bool_),
            ),
            states,
        )

    def primitive(self, states):
        return jax.pure_callback(
            self._primitive_host,
            jax.ShapeDtypeStruct(states.shape, jnp.uint8),
            states,
        )

    def level_of(self, states):
        return jax.pure_callback(
            self._level_host,
            jax.ShapeDtypeStruct(states.shape, jnp.int32),
            states,
        )
