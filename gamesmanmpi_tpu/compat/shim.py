"""Compat shim: unmodified reference-style game modules.

Two paths (see package docstring):

  solve_module(module)   — host solve via the memoized-negamax oracle. The
                           reference's own execution model (per-position
                           Python calls) at single-process scale; correct for
                           any acyclic game with hashable positions.
  TensorizedModule(...)  — lifts a scalar module onto the TensorGame protocol
                           with jax.pure_callback, so the *same jitted
                           level-synchronous engine* (and sharded solver)
                           drives an unmodified plugin. Positions must be
                           ints (they are in the reference's shipped games:
                           "position packed as int", SURVEY.md §2.2), and a
                           topological `level_fn` must exist — module
                           attribute `level_of`, or passed explicitly.
                           Deliberately slow (host round-trip per batch,
                           SURVEY.md §7) and excluded from benchmarks.
"""

from __future__ import annotations

import importlib.util
import pathlib

import jax
import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.core.bitops import SENTINEL
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame
from gamesmanmpi_tpu.solve.oracle import (
    module_api,
    normalize_value,
    oracle_solve,
)


def load_game_module(path):
    """Dynamic plugin import, the solver_launcher.py way (SURVEY.md §3.1):
    load a Python file, validate the 4-function API, return the module."""
    path = pathlib.Path(path)
    spec = importlib.util.spec_from_file_location(path.stem, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    module_api(module)  # validates required attributes
    return module


def solve_module(module):
    """Solve an unmodified reference-style module on host.

    Returns (value, remoteness, table) — table maps every reachable position
    to (value, remoteness), the same observable output as the reference.
    """
    return oracle_solve(module)


class TensorizedModule(TensorGame):
    """A scalar 4-function module lifted onto the batched TensorGame API."""

    _instance_counter = 0

    def __init__(
        self,
        module,
        *,
        max_moves: int | None = None,
        level_fn=None,
        max_level_jump: int | None = None,
        num_levels: int | None = None,
    ):
        initial, gen, do, prim = module_api(module)
        if not isinstance(initial, (int, np.integer)):
            raise TypeError(
                "TensorizedModule needs int-packed positions; use "
                "solve_module() for arbitrary hashable positions"
            )
        self._gen, self._do, self._prim = gen, do, prim
        self._initial = np.uint64(initial)
        self.name = f"compat_{getattr(module, '__name__', 'module')}"
        # Unlike built-in games, `name` does not encode this wrapper's full
        # identity (two modules can share a file stem; max_moves/level_fn are
        # caller-supplied), so the base cache_key contract — equal key =>
        # identical kernels — would not hold and the engine's kernel cache
        # could reuse another module's host callbacks. A per-instance token
        # plus a per-instance cache dict (engine.get_kernel honors it)
        # disables cross-instance sharing AND lets the kernels be collected
        # with this wrapper instead of living in the process-wide cache.
        TensorizedModule._instance_counter += 1
        self._cache_token = TensorizedModule._instance_counter
        self._private_kernel_cache: dict = {}
        level_fn = level_fn or getattr(module, "level_of", None)
        if level_fn is None:
            raise ValueError(
                "a topological level function is required: pass level_fn= or "
                "define level_of(pos) in the module (see games/base.py)"
            )
        self._level_fn = level_fn
        if max_moves is None:
            max_moves = getattr(module, "max_moves", None)
        if max_moves is None:
            # Guessing from one position would under-size boards where moves
            # open up later and abort mid-solve from inside pure_callback.
            raise ValueError(
                "max_moves is required: pass max_moves= or define max_moves "
                "in the module (the static [B, M] kernel width)"
            )
        self.max_moves = int(max_moves)
        self.max_level_jump = int(
            max_level_jump or getattr(module, "max_level_jump", 1)
        )
        self.num_levels = int(num_levels or getattr(module, "num_levels", 1 << 20))

    @property
    def cache_key(self):
        return (type(self).__qualname__, self.name, self._cache_token)

    def initial_state(self) -> np.uint64:
        return self._initial

    # Host callbacks — one python round-trip per batch, not per position.

    def _expand_host(self, states):
        states = np.asarray(states, np.uint64)
        B = states.shape[0]
        kids = np.full((B, self.max_moves), SENTINEL, dtype=np.uint64)
        mask = np.zeros((B, self.max_moves), dtype=bool)
        for i, s in enumerate(states):
            if s == SENTINEL:
                continue
            pos = int(s)
            if normalize_value(self._prim(pos)) != UNDECIDED:
                continue
            moves = list(self._gen(pos))
            if len(moves) > self.max_moves:
                raise ValueError(
                    f"position {pos:#x} has {len(moves)} moves > "
                    f"max_moves={self.max_moves}; raise max_moves"
                )
            for j, m in enumerate(moves):
                kids[i, j] = self._do(pos, m)
                mask[i, j] = True
        return kids, mask

    def _primitive_host(self, states):
        states = np.asarray(states, np.uint64)
        out = np.zeros(states.shape, dtype=np.uint8)
        for i, s in enumerate(states):
            if s != SENTINEL:
                out[i] = normalize_value(self._prim(int(s)))
        return out

    def _level_host(self, states):
        states = np.asarray(states, np.uint64)
        out = np.zeros(states.shape, dtype=np.int32)
        for i, s in enumerate(states):
            if s != SENTINEL:
                out[i] = self._level_fn(int(s))
        return out

    # TensorGame protocol: pure_callback keeps the engine jittable.

    def expand(self, states):
        shape = states.shape + (self.max_moves,)
        return jax.pure_callback(
            self._expand_host,
            (
                jax.ShapeDtypeStruct(shape, jnp.uint64),
                jax.ShapeDtypeStruct(shape, jnp.bool_),
            ),
            states,
        )

    def primitive(self, states):
        return jax.pure_callback(
            self._primitive_host,
            jax.ShapeDtypeStruct(states.shape, jnp.uint8),
            states,
        )

    def level_of(self, states):
        return jax.pure_callback(
            self._level_host,
            jax.ShapeDtypeStruct(states.shape, jnp.int32),
            states,
        )
