"""compat: run unmodified reference-style game modules.

BASELINE.json's north star requires the plugin boundary preserved "so any
game plugin (TicTacToe, Connect4, ...) runs unmodified". A reference-style
module (scalar `initial_position` / `gen_moves` / `do_move` / `primitive`,
SURVEY.md §2.1.1) can be:

  - solved directly on host (solve_module) — the compat execution path,
    correct for any acyclic game, deliberately simple and clearly not the
    benchmarked TPU path (SURVEY.md §7: "never let it leak into the
    benchmarked path");
  - lifted onto the batched TensorGame protocol (TensorizedModule) via
    host callbacks, so the same jitted engine drives it — the boundary
    proof, used by the parity tests.
"""

from gamesmanmpi_tpu.compat.shim import (
    load_game_module,
    solve_module,
    solve_module_jitted,
    TensorizedModule,
)

__all__ = [
    "load_game_module",
    "solve_module",
    "solve_module_jitted",
    "TensorizedModule",
]
