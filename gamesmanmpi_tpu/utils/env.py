"""Shared env-knob parsing: the one home for os.environ *reads*.

Two degradation contracts live here:

* warn-and-default (``env_int``/``env_float``) — malformed values must
  not break package import or a running server; they warn and fall
  back. Every numeric ``GAMESMAN_*`` knob follows it.
* fail-fast (``env_int_strict``) — knobs that exist for chip A/B runs,
  where a typo silently falling back would record two identical
  configurations; they raise with a clear message.

``env_str``/``env_opt`` are the string forms (trivial on purpose: the
point is that gamesman-lint's GM301 forbids raw ``os.environ`` reads
everywhere else, so every read is greppable here and auditable against
docs/CONFIG.md). solve/engine.py predates this module and re-exports
``_env_int``/``_env_float`` for the sharded engine; new subsystems
import from here.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, str(default))
    try:
        return int(raw)
    except ValueError:
        warnings.warn(f"{name}={raw!r} is not an integer; using {default}")
        return default


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, str(default))
    try:
        return float(raw)
    except ValueError:
        warnings.warn(f"{name}={raw!r} is not a number; using {default}")
        return default


def env_int_strict(name: str, default: int) -> int:
    """Integer env knob that fails fast with a clear message (A/B knobs
    where a silent fallback would measure the wrong configuration)."""
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None


def env_bool(name: str, default: bool) -> bool:
    """On/off env knob: "0"/"off"/"false"/"no" (any case) is False,
    "1"/"on"/"true"/"yes" is True; anything else warns and falls back
    (the warn-and-default contract — a typo'd toggle must not crash a
    server, and must not silently flip the feature either way)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    low = raw.strip().lower()
    if low in ("0", "off", "false", "no"):
        return False
    if low in ("1", "on", "true", "yes"):
        return True
    warnings.warn(f"{name}={raw!r} is not a boolean; using {default}")
    return default


def env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


def env_opt(name: str) -> Optional[str]:
    """The unset-able string form: None when the var is absent (or
    empty-meaning-unset is the caller's call to make)."""
    return os.environ.get(name)
