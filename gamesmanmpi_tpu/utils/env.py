"""Shared env-knob parsing: warn-and-default numeric reads.

One home for the degradation contract every numeric `GAMESMAN_*` knob
follows (malformed values must not break package import or a running
server — they warn and fall back). solve/engine.py predates this module
and keeps local twins for its public `_env_int`/`_env_float` (imported
by the sharded engine); new subsystems import from here.
"""

from __future__ import annotations

import os
import warnings


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, str(default))
    try:
        return int(raw)
    except ValueError:
        warnings.warn(f"{name}={raw!r} is not an integer; using {default}")
        return default


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, str(default))
    try:
        return float(raw)
    except ValueError:
        warnings.warn(f"{name}={raw!r} is not a number; using {default}")
        return default
