"""utils: observability and persistence (SURVEY.md §5).

The reference has stdout prints and nothing else (§5.1-5.5 all "none");
these are the TPU-idiomatic equivalents the rebuild is required to carry:
structured per-level metrics (metrics.py), level checkpoint/restart
(checkpoint.py), and profiler capture (profiling.py).
"""

from gamesmanmpi_tpu.utils.metrics import JsonlLogger, StdoutLogger
from gamesmanmpi_tpu.utils.checkpoint import LevelCheckpointer, save_result_npz
from gamesmanmpi_tpu.utils.profiling import maybe_profile

__all__ = [
    "JsonlLogger",
    "StdoutLogger",
    "LevelCheckpointer",
    "save_result_npz",
    "maybe_profile",
]
