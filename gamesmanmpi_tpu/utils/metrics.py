"""Structured per-level metrics (SURVEY.md §5.5).

Reference: stdout on rank 0 plus optional rank-tagged debug prints. Rebuild:
one structured record per solve phase per level — level, frontier size, seconds,
positions/sec — emitted as JSONL (and optionally human-readable). This is
load-bearing: BASELINE.json's tracked metric is positions-solved/sec/chip, and
bench.py computes it from these records.
"""

from __future__ import annotations

import json
import os
import sys
import time


class _ClosingLogger:
    """Context-manager protocol shared by every logger: `with` guarantees
    the file handle closes on exceptions (long-lived consumers — the CLI,
    the query server — would otherwise leak handles / lose buffered tail
    records on an aborted solve)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class JsonlLogger(_ClosingLogger):
    """Appends one JSON object per record to a file."""

    def __init__(self, path: str):
        self.path = path
        self._fh = open(path, "a", buffering=1)
        self._t0 = time.time()

    def log(self, record: dict) -> None:
        rec = {"t": round(time.time() - self._t0, 6), **record}
        self._fh.write(json.dumps(rec, default=str) + "\n")

    def close(self) -> None:
        """Flush-and-fsync, tolerating double-close: an aborted solve's
        teardown may close both via the context manager and an explicit
        close, and the tail records (the evidence of WHERE it died) must
        be durable on disk, not in a lost OS buffer."""
        if self._fh.closed:
            return
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except (OSError, ValueError):
            pass  # fs without fsync / already-invalid fd: best effort
        finally:
            self._fh.close()


class StdoutLogger(_ClosingLogger):
    """Human-readable per-level progress lines (debug flag analog)."""

    def log(self, record: dict) -> None:
        phase = record.get("phase", "?")
        level = record.get("level", "-")
        parts = [
            f"{k}={v}" for k, v in record.items() if k not in ("phase", "level")
        ]
        print(f"[{phase}] level={level} " + " ".join(parts), file=sys.stderr)

    def close(self) -> None:
        pass


class TagLogger(_ClosingLogger):
    """Stamp every record with constant fields (a record's own value for
    a key wins over the stamp). The serving fleet stamps ``worker=<id>``
    on each worker's JSONL stream so tools/obs_report.py can merge N
    workers' ``serve_batch`` records without ambiguity."""

    def __init__(self, inner, **tags):
        self.inner = inner
        self.tags = tags

    def log(self, record: dict) -> None:
        merged = {**self.tags, **record}
        self.inner.log(merged)

    def close(self) -> None:
        self.inner.close()


class RankLogger(TagLogger):
    """Stamp every record with the emitting process's rank.

    A multi-process solve writes one JSONL stream per rank (same
    schema); without the stamp the merged streams are rank-ambiguous and
    tools/obs_report.py cannot tell "two ranks timed the same level"
    (wall-clock: take the max) from "one rank retried it" (accumulate).
    """

    def __init__(self, inner, rank: int):
        super().__init__(inner, rank=int(rank))


class TeeLogger(_ClosingLogger):
    """Fan a record out to several loggers."""

    def __init__(self, *loggers):
        self.loggers = [l for l in loggers if l is not None]

    def log(self, record: dict) -> None:
        for l in self.loggers:
            l.log(record)

    def close(self) -> None:
        for l in self.loggers:
            l.close()
