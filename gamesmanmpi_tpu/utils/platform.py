"""Backend/platform selection that works around plugin-pinned containers.

Some environments register an accelerator PJRT plugin in sitecustomize and pin
`jax_platforms` at interpreter start. That makes the standard JAX_PLATFORMS
env var ineffective (the config wins) and can hang CPU-only runs at first
backend init. The one reliable knob is the jax config, set before backends
initialize — this helper is the single place that knowledge lives
(used by the CLI, the driver entry points, and tests/conftest.py).
"""

from __future__ import annotations

import os

from gamesmanmpi_tpu.utils.env import env_opt, env_str

# Bumped every time force_platform actually clears initialized backends.
# Kernel caches (solve/engine.py _cache_key) mix this into their keys:
# executables closed over pre-clear device/Mesh objects would otherwise be
# reused after a clear and die with "incompatible devices for jitted
# computation" (the exact failure the full suite hit when every in-process
# CLI run re-forced an already-active CPU backend).
_BACKEND_EPOCH = 0


def backend_epoch() -> int:
    return _BACKEND_EPOCH


def force_platform(platform: str, fake_devices: int | None = None) -> None:
    """Select a JAX platform robustly; optionally fake N host devices.

    Must run before the first jax array/device operation for the XLA_FLAGS
    part to take effect. No-op (beyond config settles) when the requested
    platform is already the active backend — clearing live backends orphans
    every cached executable keyed on their device objects. If a genuine
    switch is needed and backends are initialized, they are cleared and the
    backend epoch is bumped (pre-existing arrays keep their original
    backend; epoch-keyed kernel caches rebuild lazily).
    """
    flags_changed = False
    if fake_devices is not None and platform == "cpu":
        flags = env_str("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={fake_devices}"
            ).strip()
            flags_changed = True

    import jax

    # Config first: clearing/initializing backends re-reads the config, and
    # initializing a pinned plugin backend is exactly what can hang.
    jax.config.update("jax_platforms", platform)
    jax.config.update("jax_enable_x64", True)

    from jax._src import xla_bridge

    if not xla_bridge.backends_are_initialized():
        return

    if not flags_changed:
        # Already initialized: if the active default backend IS the
        # requested platform, clearing would only poison kernel caches.
        # (flags_changed means the device count just changed, so the
        # existing CPU backend is stale and must be rebuilt regardless.)
        try:
            current = jax.default_backend()
        except Exception:  # pragma: no cover - backend probe never raised
            current = None
        if current == platform:
            return

    from jax.extend.backend import clear_backends

    clear_backends()
    global _BACKEND_EPOCH
    _BACKEND_EPOCH += 1


def force_cpu_if_requested(fake_devices: int | None = None) -> bool:
    """Honor a JAX_PLATFORMS env var that asks for the CPU backend.

    In plugin-pinned containers the env var alone is ineffective (the
    startup config wins) and the first backend touch can HANG at plugin
    init — so driver entry points that may run while the accelerator
    relay is down must translate the env request into force_platform
    BEFORE any jax array operation. Returns True when it forced CPU.
    """
    requested = [
        p.strip().lower()
        for p in env_str("JAX_PLATFORMS", "").split(",")
    ]
    if "cpu" not in requested:
        return False
    force_platform("cpu", fake_devices=fake_devices)
    return True


def apply_platform_env(default_fake_devices: int | None = None) -> None:
    """Honor GAMESMAN_PLATFORM (and GAMESMAN_FAKE_DEVICES) if set."""
    platform = env_opt("GAMESMAN_PLATFORM")
    if not platform:
        return
    fake = env_opt("GAMESMAN_FAKE_DEVICES")
    fake_devices = int(fake) if fake else default_fake_devices
    force_platform(platform, fake_devices)


# DELIBERATE TWIN of bench.py's _PROBE_SRC (same staged prints, same
# PROBE_OK protocol, same faulthandler deadline trick): bench's parent
# process must never import jax, and this package's __init__ imports jax
# at module level, so bench cannot reuse this module — a fix to either
# probe source must be mirrored in the other.
_PROBE_SRC = r"""
import faulthandler, sys, time
# If init wedges, print every thread's stack to stderr before the parent's
# deadline so the parent can capture *where* it hung (relay dial, compile
# RPC, device enumeration, ...).
faulthandler.dump_traceback_later({dump_after}, exit=False, file=sys.stderr)
t0 = time.time()
import jax
print(f"probe: jax imported in {{time.time()-t0:.1f}}s", file=sys.stderr)
t0 = time.time()
devs = jax.devices()
print(f"probe: jax.devices() -> {{devs}} in {{time.time()-t0:.1f}}s",
      file=sys.stderr)
import jax.numpy as jnp
t0 = time.time()
x = jnp.arange(1024, dtype=jnp.uint32)
y = jnp.sort(x).block_until_ready()
print(f"probe: first kernel in {{time.time()-t0:.1f}}s", file=sys.stderr)
faulthandler.cancel_dump_traceback_later()
print("PROBE_OK", devs[0].platform)
"""


def probe_backend(timeout: float) -> str | None:
    """Probe backend init in a throwaway subprocess, under a deadline.

    Returns the platform string on success, None on failure/hang. The
    relayed accelerator backend's observed failure mode is WEDGING at
    first touch (no error, no timeout of its own) — an in-process solve
    would hang >300 s with zero output. The subprocess inherits the
    environment, costs one jax import, and on a hang dumps every thread's
    stack to stderr shortly before the deadline so the operator sees
    *where* it hung (relay dial, compile RPC, device enumeration). The
    same probe bench.py has always run (see the twin-source note on
    _PROBE_SRC), shared here so the bare CLI fails fast too (VERDICT r5).
    """
    import subprocess
    import sys

    src = _PROBE_SRC.format(dump_after=max(timeout - 15.0, 5.0))
    try:
        proc = subprocess.run(
            [sys.executable, "-c", src],
            timeout=timeout, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired as e:
        for stream in (e.stderr, e.stdout):
            if stream:
                sys.stderr.write(
                    stream if isinstance(stream, str)
                    else stream.decode(errors="replace")
                )
        print(f"backend probe: timed out after {timeout:.0f}s "
              "(stacks above)", file=sys.stderr)
        return None
    if proc.returncode == 0:
        for line in proc.stdout.splitlines():
            if line.startswith("PROBE_OK"):
                return line.split()[1]
    if proc.stderr:
        sys.stderr.write(proc.stderr)
    print(f"backend probe: child exited rc={proc.returncode}",
          file=sys.stderr)
    return None


def platform_auto_flag(name: str, accel: str, cpu: str,
                       choices: tuple[str, ...]) -> str:
    """Resolve an env knob with platform-auto default, strictly.

    Reads os.environ[name]; "auto"/unset resolves to `accel` on
    accelerators and `cpu` on the CPU backend (decided at call time — the
    kernel builders call this at cache-key time). Any other value must be
    in `choices`; unknown values raise instead of silently measuring the
    auto default — these knobs exist for chip A/B runs, where a typo that
    falls back to auto records two identical configurations.
    """
    raw = env_str(name, "auto")
    if raw in choices:
        return raw
    if raw != "auto":
        raise ValueError(
            f"{name}={raw!r}: expected one of {('auto',) + choices}"
        )
    import jax

    return accel if jax.default_backend() != "cpu" else cpu


def platform_auto_bool(name: str, accel: bool, cpu: bool) -> bool:
    """Boolean twin of platform_auto_flag ("1"/"on"/"true", "0"/"off"/
    "false", "auto"/unset; anything else raises)."""
    on, off = ("1", "on", "true"), ("0", "off", "false")
    raw = env_str(name, "auto").lower()
    if raw in on:
        return True
    if raw in off:
        return False
    if raw != "auto":
        raise ValueError(
            f"{name}={raw!r}: expected auto, {'/'.join(on)} or "
            f"{'/'.join(off)}"
        )
    import jax

    return accel if jax.default_backend() != "cpu" else cpu
