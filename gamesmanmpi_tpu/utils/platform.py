"""Backend/platform selection that works around plugin-pinned containers.

Some environments register an accelerator PJRT plugin in sitecustomize and pin
`jax_platforms` at interpreter start. That makes the standard JAX_PLATFORMS
env var ineffective (the config wins) and can hang CPU-only runs at first
backend init. The one reliable knob is the jax config, set before backends
initialize — this helper is the single place that knowledge lives
(used by the CLI, the driver entry points, and tests/conftest.py).
"""

from __future__ import annotations

import os


def force_platform(platform: str, fake_devices: int | None = None) -> None:
    """Select a JAX platform robustly; optionally fake N host devices.

    Must run before the first jax array/device operation for the XLA_FLAGS
    part to take effect; if backends already initialized, they are cleared
    (pre-existing arrays keep their original backend).
    """
    if fake_devices is not None and platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={fake_devices}"
            ).strip()

    import jax

    # Config first: clearing/initializing backends re-reads the config, and
    # initializing a pinned plugin backend is exactly what can hang.
    jax.config.update("jax_platforms", platform)
    jax.config.update("jax_enable_x64", True)

    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()


def force_cpu_if_requested(fake_devices: int | None = None) -> bool:
    """Honor a JAX_PLATFORMS env var that asks for the CPU backend.

    In plugin-pinned containers the env var alone is ineffective (the
    startup config wins) and the first backend touch can HANG at plugin
    init — so driver entry points that may run while the accelerator
    relay is down must translate the env request into force_platform
    BEFORE any jax array operation. Returns True when it forced CPU.
    """
    requested = [
        p.strip().lower()
        for p in os.environ.get("JAX_PLATFORMS", "").split(",")
    ]
    if "cpu" not in requested:
        return False
    force_platform("cpu", fake_devices=fake_devices)
    return True


def apply_platform_env(default_fake_devices: int | None = None) -> None:
    """Honor GAMESMAN_PLATFORM (and GAMESMAN_FAKE_DEVICES) if set."""
    platform = os.environ.get("GAMESMAN_PLATFORM")
    if not platform:
        return
    fake = os.environ.get("GAMESMAN_FAKE_DEVICES")
    fake_devices = int(fake) if fake else default_fake_devices
    force_platform(platform, fake_devices)
