"""Per-level checkpoint / resume (SURVEY.md §5.4).

The reference has no checkpointing — a solve is monolithic and in-memory.
For the north-star scale (4.5e12 states on a preemptible pod) restart-from-
level recovery is required. The unit of persistence is the natural unit of
the level-synchronous engine: one solved level = (sorted states, packed
value+remoteness cells via core.codec). Plain .npz per level plus a JSON
manifest — no framework dependency, shard-friendly, and the packed cell
format is exactly the HBM table layout.
"""

from __future__ import annotations

import json
import os
import pathlib

import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.compress import (
    CELL_CANDIDATES,
    DEFAULT_BLOCK_POSITIONS,
    GENERIC_CANDIDATES,
    KEY_CANDIDATES,
    encode_array,
)
from gamesmanmpi_tpu.core.codec import (
    pack_cells,
    unpack_cells,
    unpack_cells_np,
)
from gamesmanmpi_tpu.resilience import faults
# The sealed-read path (crc verify, torn-error tuple, the one np.load
# door) and the async engine live in store/ now; the names below are
# re-exports so every historical import site keeps working. ISSUE 11
# deleted the private copies — this module holds the npz FRAMING and
# the manifest/seal logic, the store holds the I/O.
from gamesmanmpi_tpu.store import (
    BLOCKS_META_MEMBER,
    CorruptSealError as CorruptCheckpointError,  # noqa: F401 - re-export
    TORN_SEAL_ERRORS as TORN_NPZ_ERRORS,
    default_store,
    file_crc32,
    file_key,
    loadz as _loadz,  # noqa: F401 - re-export (tests compare tables)
    read_npz_members,
)
from gamesmanmpi_tpu.utils.env import env_int, env_str


def _verify_enabled() -> bool:
    return env_str("GAMESMAN_CKPT_VERIFY", "1") not in (
        "0", "off", "false"
    )


def reshard_enabled() -> bool:
    """GAMESMAN_RESHARD (default on): may a resume adopt a checkpoint
    tree sealed at a DIFFERENT geometry (shard count, world size) by
    re-partitioning rows through the owner hash on load? Off pins
    resume to the sealed geometry — any mismatch raises
    :class:`CheckpointGeometryError` naming both geometries instead of
    silently adapting (or silently re-running forward from the root,
    the pre-elastic behavior)."""
    return env_str("GAMESMAN_RESHARD", "1") not in ("0", "off", "false")


class CheckpointGeometryError(ValueError):
    """A checkpoint tree's sealed geometry cannot (or — with
    GAMESMAN_RESHARD=0 — may not) serve the requested solve geometry.
    The message names the sealed vs requested (shards, world, epoch)
    so an operator never diagnoses an opaque resume abort."""


def repartition_rows(states, num_shards: int, *payloads):
    """Bucket one shard's rows by the owner hash at ``num_shards``.

    The elastic-resume primitive: ``states`` (any sorted or unsorted
    slice of the hash-partitioned space) splits into ``num_shards``
    buckets by the SAME splitmix64 owner hash the live solve routes
    with, and every ``payloads`` column stays row-aligned through the
    split. Returns ``[(states_t, *payloads_t) for t in range(S')]``
    with input row order preserved inside each bucket.
    """
    from gamesmanmpi_tpu.core.hashing import owner_shard_np

    states = np.asarray(states)
    payloads = tuple(np.asarray(p) for p in payloads)
    owners = owner_shard_np(states, num_shards)
    out = []
    for t in range(num_shards):
        sel = owners == t
        out.append((states[sel],) + tuple(p[sel] for p in payloads))
    return out


def reshard_shard_stream(load_shard, old_count: int, new_count: int):
    """Streamed shard-set re-partitioner: one sealed artifact set at S
    shards becomes per-shard arrays at S' shards.

    ``load_shard(s) -> (states, *payloads)`` pulls ONE old shard at a
    time (the callers pass the block-store-served sealed readers, so
    decoded-file residency is one old shard; the output — one level at
    the new geometry — is the caller's to hold, exactly what it was
    about to keep resident anyway). Rows bucket by the owner hash at
    ``new_count`` and each new shard's columns are sorted by state —
    the per-shard sorted invariant every consumer relies on. Payload
    columns stay row-aligned through both the partition and the sort.
    """
    frags: list = [[] for _ in range(new_count)]
    width = None
    for s in range(old_count):
        arrs = load_shard(s)
        if not isinstance(arrs, tuple):
            arrs = (arrs,)
        width = len(arrs)
        for t, part in enumerate(
            repartition_rows(arrs[0], new_count, *arrs[1:])
        ):
            frags[t].append(part)
    out = []
    for t in range(new_count):
        cols = [
            np.concatenate([f[i] for f in frags[t]])
            for i in range(width or 1)
        ]
        order = np.argsort(cols[0], kind="stable")
        out.append(tuple(c[order] for c in cols))
    return out


def _block_candidates(name: str, arr: np.ndarray):
    """Codec candidates by member shape (compress/codecs): sorted state
    arrays delta-code, packed uint32 cells split value/remoteness, and
    everything else (edge indices, slot maps) gets the DEFLATE backstop
    — raw passthrough always competes, so a pathological member can only
    tie, never lose."""
    if arr.dtype == np.uint32 and name.startswith("cells"):
        return CELL_CANDIDATES
    if arr.dtype.kind == "u":
        # states / frontier levels / keys: sorted by the engine's
        # invariants; keydelta declines gracefully if one is not.
        return KEY_CANDIDATES
    return GENERIC_CANDIDATES


def _savez(path, allow_block_framing=True, **arrays) -> tuple[int, int]:
    """Atomic npz write: tmp + os.replace. -> (raw bytes, stored bytes).

    allow_block_framing=False pins the PLAIN npz layout regardless of
    GAMESMAN_CKPT_COMPRESS=blocks: user-facing artifacts (``--table-out``
    tables via save_result_npz/save_table_npz) are consumed by plain
    np.load outside this repo, and a checkpoint knob must never silently
    change their format (framed members would read as uint8 bytes, not
    states). zip-level DEFLATE still applies — np.load understands it.

    Atomicity (ADVICE r5): resumed runs RE-save levels whose files already
    exist while the manifest still seals them — a death mid-overwrite
    would otherwise leave a sealed-but-truncated npz that kills the next
    resume with zipfile.BadZipFile instead of degrading to the intact
    prefix. The tmp name is per-writer (pid), same discipline as the
    manifest's.

    Compression (GAMESMAN_CKPT_COMPRESS):

    * ``auto`` (default) — np.savez_compressed below ~64 MB, raw npz
      above: small-game checkpoints stay tidy, big-run payloads write at
      disk speed (zlib over high-entropy packed bitboards costs
      ~50 MB/s/core for single-digit savings).
    * ``0``/``1`` — force raw / force zip-level DEFLATE.
    * ``blocks`` — the ISSUE 9 format: each 1-D member is framed into
      independently-decodable blocks (compress/blocks — keydelta for
      sorted states, cellpack for packed cells, raw when compression
      loses) inside an UNCOMPRESSED npz, with the per-member index in a
      ``__blocks__`` JSON member. Loaders go through :func:`_loadz`,
      which decodes transparently; a torn/bit-rotted block raises
      BlockCorruptError (a ValueError — already in TORN_NPZ_ERRORS), so
      the quarantine-and-degrade resume paths treat compressed
      corruption exactly like v1 torn files. Plain npz files keep
      loading regardless of the knob (resume across a flag flip works).
    """
    total = sum(a.nbytes for a in arrays.values())
    flag = env_str("GAMESMAN_CKPT_COMPRESS", "auto")
    if flag == "auto":
        compress = total < (64 << 20)
    else:
        compress = flag not in ("0", "off", "false")
    path = pathlib.Path(path)
    if path.suffix != ".npz":
        # np.savez appends .npz to extension-less paths; the atomic
        # tmp+replace write must keep that contract (`--table-out results`
        # has always produced results.npz — silently writing `results`
        # would leave a stale results.npz for consumers to read).
        path = path.with_name(path.name + ".npz")
    tmp = path.with_suffix(f".{os.getpid()}.tmp.npz")
    try:
        if flag == "blocks" and not allow_block_framing:
            compress = total < (64 << 20)  # the "auto" contract
        if flag == "blocks" and allow_block_framing:
            members, meta = {}, {}
            bp = env_int("GAMESMAN_DB_BLOCK", DEFAULT_BLOCK_POSITIONS)
            if bp <= 0:
                # Warn-and-default (the env-knob degradation contract):
                # a nonsensical block size must not kill a multi-hour
                # solve at its FIRST checkpoint seal. DbWriter validates
                # the same knob at construction; checkpoint writes have
                # no construction moment, so degrade here.
                import warnings

                warnings.warn(
                    f"GAMESMAN_DB_BLOCK={bp} is not positive; using "
                    f"{DEFAULT_BLOCK_POSITIONS}"
                )
                bp = DEFAULT_BLOCK_POSITIONS
            for name, a in arrays.items():
                arr = np.asarray(a)
                if arr.ndim != 1 or arr.dtype.hasobject:
                    members[name] = arr  # stored plain, absent from meta
                    continue
                index, blobs = encode_array(arr, bp, _block_candidates(
                    name, arr
                ))
                members[name] = np.frombuffer(
                    b"".join(blobs), dtype=np.uint8
                )
                meta[name] = index
            members[BLOCKS_META_MEMBER] = np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8
            )
            # Uncompressed zip: the payload is already entropy-coded
            # per block; zipping it again costs CPU for ~nothing.
            np.savez(tmp, **members)
        elif compress:
            np.savez_compressed(tmp, **arrays)
        else:
            np.savez(tmp, **arrays)
        stored = tmp.stat().st_size
        os.replace(tmp, path)
        return total, stored
    finally:
        tmp.unlink(missing_ok=True)


class LevelCheckpointer:
    """Saves solved levels as they complete; loads them for resume.

    All payload I/O routes through the block store (``store=``, default
    the process-wide :func:`default_store`): sealed reads go through the
    store's cache (so a level hinted by the solver's readahead is
    decoded before the solve thread asks), payload writes go
    write-behind (the solve thread never waits on DEFLATE+fsync), and
    every ``finish_*`` seal waits for its payload writes first — the
    GM8xx ordering invariant, chaos-verified at ``store.writebehind``.
    """

    def __init__(self, directory: str, store=None):
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.manifest_path = self.dir / "manifest.json"
        self._store = store

    @property
    def store(self):
        """The block store serving this checkpointer (late-bound: the
        default store re-reads its env knobs, so a test flipping
        GAMESMAN_STORE_* between solves gets the fresh config)."""
        return self._store if self._store is not None else default_store()

    def flush_writes(self) -> None:
        """Barrier on pending write-behind payload writes (re-raising
        the first failure). Every seal path calls this unless its
        caller already waited on the specific tickets (the sharded
        solver's pipelined seals pass ``drain=False``)."""
        self.store.drain()

    # ------------------------------------------------------ sealed reads
    # The one read door (store.read over store/sealed.read_npz_members):
    # crc-verified, cache-served, prefetch-aware. Loaders are pure —
    # corruption discovered on a prefetch thread re-raises HERE, on the
    # consuming thread, where quarantine decisions live.

    def _npz_read_plan(self, path, names, manifest=None):
        """(key, loader) for one sealed npz payload — the SAME plan for
        hints and reads, so a hinted load is always a later cache hit."""
        want = None
        if _verify_enabled():
            if manifest is None:
                manifest = self.load_manifest()
            want = manifest.get("crc", {}).get(pathlib.Path(path).name)
        return file_key(path), (
            lambda: read_npz_members(path, names, crc=want)
        )

    def _read_npz(self, path, names, manifest=None):
        """Sealed members of one checkpoint npz, through the store."""
        key, loader = self._npz_read_plan(path, names, manifest)
        return self.store.read(key, loader)

    def _hint_npz(self, path, names, manifest=None) -> None:
        """Readahead hint for one sealed npz (decoded on the prefetch
        pool; a later _read_npz of the same unchanged file is a cache
        hit; an evicted or changed file degrades to a sync read)."""
        key, loader = self._npz_read_plan(path, names, manifest)
        self.store.hint(key, loader)

    def _level_path(self, level: int) -> pathlib.Path:
        return self.dir / f"level_{level:04d}.npz"

    def _write_manifest(self, manifest: dict) -> None:
        """Atomic replace, never truncate-in-place: under multi-host, only
        process 0 writes the manifest AFTER bind — but PEERS read it
        concurrently (completed_levels at backward start races the
        post-barrier seals), and bind_game itself writes from EVERY
        process at solve start. A torn read crashed a two-process run
        with JSONDecodeError (round 4); os.replace guarantees readers
        see old-or-new, never partial. The temp name is per-writer
        (pid): concurrent binders sharing one .tmp consumed each other's
        rename (FileNotFoundError — same lesson as the counts cache's
        private-per-writer tmp)."""
        tmp = self.manifest_path.with_suffix(f".json.{os.getpid()}.tmp")
        tmp.write_text(json.dumps(manifest))
        os.replace(tmp, self.manifest_path)

    # ------------------------------------------------------------ integrity
    # Per-file crc32, recorded in the manifest when a file is sealed and
    # verified when it is loaded for resume (store/sealed.verify_crc,
    # captured into each sealed-read plan). Atomic _savez already rules
    # out torn WRITES; the crc catches what atomicity cannot — silent
    # bit-rot, a partial overwrite by a foreign process, a filesystem
    # that lied about durability. A mismatching file is quarantined
    # (renamed .corrupt, unsealed from the manifest) by the CONSUMING
    # thread — the pure read may have run on a prefetch thread — and
    # the loader raises CorruptCheckpointError, which every
    # TORN_NPZ_ERRORS degrade path already turns into "recompute this
    # level from the intact prefix".

    def quarantine_level(self, level: int) -> None:
        """Rename a sealed level's file(s) to ``.corrupt`` and unseal it,
        so the run degrades to the intact prefix: the level recomputes
        (its frontier is still known) and re-seals over the quarantine.
        Idempotent — callers may race the loader's own quarantine."""
        self.flush_writes()  # never quarantine around an in-flight write
        manifest = self.load_manifest()
        paths = [self._level_path(level)]
        num = manifest.get("sharded_levels", {}).get(str(level))
        if num:
            paths += [self._shard_level_path(level, s) for s in range(num)]
        crc = manifest.get("crc", {})
        for p in paths:
            if p.exists():
                p.rename(p.with_name(p.name + ".corrupt"))
            crc.pop(p.name, None)
        if level in manifest.get("levels", []):
            manifest["levels"] = [
                l for l in manifest["levels"] if l != level
            ]
        manifest.get("sharded_levels", {}).pop(str(level), None)
        self._write_manifest(manifest)

    def quarantine_and_log(self, level: int, exc, logger=None) -> None:
        """The one degrade contract every resume path shares: quarantine
        the level's sealed files and emit the ``ckpt_degraded`` record
        (phase name + 200-char error truncation live HERE, not at three
        call sites)."""
        self.quarantine_level(level)
        if logger is not None:
            logger.log({
                "phase": "ckpt_degraded", "level": int(level),
                "error": str(exc)[:200],
            })

    def _quarantine_frontier(self, level: int) -> None:
        """Quarantine one incrementally-saved frontier level and truncate
        the discovery prefix there: every deeper frontier is unsealed too
        (the resume contract is contiguous-from-root), and the
        ``frontiers_complete`` flag drops so the engine re-expands from
        the surviving prefix instead of trusting a holed snapshot."""
        manifest = self.load_manifest()
        crc = manifest.get("crc", {})
        kept, dropped = [], []
        for k in manifest.get("forward_levels", []):
            (kept if int(k) < level else dropped).append(int(k))
        for k in dropped:
            p = self.dir / f"frontier_{k:04d}.npz"
            if k == level and p.exists():
                # Only the corrupt file is renamed; deeper levels are
                # merely unsealed (re-expansion re-saves over them).
                p.rename(p.with_name(p.name + ".corrupt"))
            crc.pop(p.name, None)
        manifest["forward_levels"] = sorted(kept)
        manifest.pop("frontiers_complete", None)
        self._write_manifest(manifest)

    # -------------------------------------------- cross-rank consistency
    # Multi-process seal stamps (ISSUE 6): each sealed artifact records
    # the run epoch it was taken in and which process rank owned each
    # shard file. Resume verifies every rank digests the SAME state
    # (ShardedSolver barriers on resume_digest) and can attribute a torn
    # or missing per-rank shard file to its writer instead of guessing.

    def stamp_run(self, num_processes: int, ranks=None) -> int:
        """Increment the manifest's run epoch (process 0, solve start).

        The epoch distinguishes seals taken by the current attempt from
        a previous (possibly differently-shaped) run's: a resumed solve
        after a rank death carries epoch N+1 while the surviving prefix
        keeps N — both valid, both loadable, but auditable."""
        manifest = self.load_manifest()
        run = manifest.get("run", {})
        epoch = int(run.get("epoch", 0)) + 1
        manifest["run"] = {
            "epoch": epoch,
            "num_processes": int(num_processes),
            "ranks": list(ranks) if ranks is not None else [],
        }
        self._write_manifest(manifest)
        return epoch

    def run_info(self) -> dict:
        """{"epoch", "num_processes", "ranks"} of the latest stamped run
        ({} for pre-distributed directories)."""
        return self.load_manifest().get("run", {})

    @staticmethod
    def _stamp_seal(manifest: dict, table: str, level: int,
                    ranks=None) -> None:
        """Record one seal's (epoch, rank-set) stamp in ``manifest``
        (caller writes the manifest — seal + stamp land atomically)."""
        manifest.setdefault(table, {})[str(level)] = {
            "epoch": int(manifest.get("run", {}).get("epoch", 0)),
            "ranks": list(ranks) if ranks is not None else [],
        }

    def resume_digest(self, num_shards: int) -> str:
        """Stable digest of everything resume decisions read: the
        deepest mutually-sealed solved level, the sealed level sets,
        the frontier snapshots, and the run epoch. Every rank computes
        it independently and barriers on it — agreement means the ranks
        share one view of the checkpoint directory; divergence aborts
        the fleet before any rank loads a different prefix.

        Geometry normalization (elastic resume): with GAMESMAN_RESHARD
        on (the default) the digest covers the DIRECTORY's sealed state
        only — the requested shard count drops out — so a W'-rank /
        S'-shard world can adopt a W-rank tree after the consistency
        barrier and reshard on load. With resharding pinned off the
        requested geometry stays in the digest (the legacy strict
        view)."""
        import hashlib

        manifest = self.load_manifest()
        completed = self.completed_levels()
        view = {
            "deepest_sealed": max(completed) if completed else None,
            "completed": completed,
            "sharded": sorted(manifest.get("sharded_levels", {})),
            "forward": sorted(manifest.get("forward_level_shards", {})),
            "frontier_shards": manifest.get("frontier_shards"),
            "frontiers": bool(manifest.get("frontiers")),
            "edges": sorted(manifest.get("edge_levels", {})),
            "epoch": manifest.get("run", {}).get("epoch", 0),
            "num_shards": None if reshard_enabled() else num_shards,
        }
        blob = json.dumps(view, sort_keys=True).encode()
        return hashlib.sha1(blob).hexdigest()

    def sealed_geometry(self, manifest=None) -> dict:
        """The geometry this tree's sealed shard artifacts were written
        at: ``{"shard_counts": sorted list of every sealed shard count
        (mixed trees happen mid-reshard), "num_shards": the single
        count or None when mixed/none, "num_processes": world size of
        the last stamped run (None pre-distributed), "epoch": run
        epoch}``. Global (non-shard) artifacts are geometry-free and do
        not participate; neither do sealed EDGE shards — their slot
        geometry never reshards (a foreign-count edge level takes the
        per-level lookup fallback structurally, pre-dating elasticity),
        so a stale consumed edge set must not hold the whole tree's
        geometry status hostage. This keeps the view in lockstep with
        the campaign's jax-free twin (``checkpoint_progress``)."""
        if manifest is None:
            manifest = self.load_manifest()
        counts = set()
        if manifest.get("frontier_shards"):
            counts.add(int(manifest["frontier_shards"]))
        for v in manifest.get("forward_level_shards", {}).values():
            counts.add(int(v))
        for v in manifest.get("sharded_levels", {}).values():
            counts.add(int(v))
        counts.discard(0)
        run = manifest.get("run", {})
        return {
            "shard_counts": sorted(counts),
            "num_shards": (
                next(iter(counts)) if len(counts) == 1 else None
            ),
            "num_processes": (
                int(run["num_processes"]) if "num_processes" in run
                else None
            ),
            "epoch": int(run.get("epoch", 0)),
        }

    def check_resume_geometry(self, num_shards: int,
                              num_processes: int = 1) -> dict:
        """The elastic-resume gate, called once at solve start: compare
        the sealed geometry against the requested one. Returns
        ``{"status": "fresh" | "match" | "reshard", "sealed": {...},
        "requested": {...}}`` — ``reshard`` means the loaders will
        re-partition rows on load (and sealed edge shards fall back to
        the per-level lookup backward). With GAMESMAN_RESHARD=0 any
        mismatch raises :class:`CheckpointGeometryError` NAMING both
        geometries — never an opaque abort, never a silent forward
        re-run."""
        sealed = self.sealed_geometry()
        requested = {
            "num_shards": int(num_shards),
            "num_processes": int(num_processes),
        }
        if not sealed["shard_counts"]:
            return {"status": "fresh", "sealed": sealed,
                    "requested": requested}
        shards_match = sealed["shard_counts"] == [int(num_shards)]
        world_match = sealed["num_processes"] in (None,
                                                 int(num_processes))
        if shards_match and world_match:
            return {"status": "match", "sealed": sealed,
                    "requested": requested}
        if not reshard_enabled():
            raise CheckpointGeometryError(
                f"checkpoint {self.dir} is sealed at "
                f"shards={sealed['shard_counts']} "
                f"world={sealed['num_processes']} "
                f"epoch={sealed['epoch']} but this solve requested "
                f"shards={num_shards} world={num_processes}, and "
                "GAMESMAN_RESHARD=0 pins resume to the sealed "
                "geometry — rerun with the sealed geometry, or unset "
                "GAMESMAN_RESHARD to reshard on load"
            )
        return {"status": "reshard", "sealed": sealed,
                "requested": requested}

    def bind_game(self, name: str) -> None:
        """Record/validate which game this directory belongs to.

        Game names encode every parameter (board, symmetry flag, ...), so a
        resume with a different spec — e.g. sym=1 against a sym=0 checkpoint,
        whose canonical tables would silently disagree — fails loudly here
        instead of mixing tables. Engines call this before loading anything.
        """
        manifest = self.load_manifest()
        bound = manifest.get("game")
        if bound is None:
            manifest["game"] = name
            self._write_manifest(manifest)
        elif bound != name:
            raise ValueError(
                f"checkpoint directory {self.dir} belongs to game {bound!r}, "
                f"not {name!r} — use a fresh --checkpoint-dir"
            )

    def save_level(self, level: int, table) -> None:
        cells = np.asarray(
            pack_cells(jnp.asarray(table.values), jnp.asarray(table.remoteness))
        )
        path = self._level_path(level)
        _savez(path, states=table.states, cells=cells)
        manifest = self.load_manifest()
        manifest["levels"] = sorted(set(manifest.get("levels", [])) | {level})
        # Seal + crc land in ONE manifest write: a death in between could
        # otherwise leave a sealed level whose crc is missing (fine — crc
        # checks are best-effort for pre-integrity files) but never a crc
        # for an unsealed level.
        manifest.setdefault("crc", {})[path.name] = file_crc32(path)
        self._write_manifest(manifest)
        faults.fire("ckpt.save_level", path=str(path), level=level)

    def load_manifest(self) -> dict:
        if self.manifest_path.exists():
            return json.loads(self.manifest_path.read_text())
        return {}

    def load_level(self, level: int):
        """Global (sorted) table of one level — from the global file, or
        assembled from per-shard files when the level was saved sharded.

        Verifies the manifest crc first; a mismatch quarantines the
        level and raises CorruptCheckpointError (a TORN_NPZ_ERRORS
        member), which resume paths degrade to a recompute."""
        from gamesmanmpi_tpu.solve.engine import LevelTable

        faults.fire("ckpt.load_level", level=level)
        path = self._level_path(level)
        if path.exists():
            try:
                states, cells = self._read_npz(path, ("states", "cells"))
            except CorruptCheckpointError:
                self.quarantine_level(level)
                raise
            values, remoteness = unpack_cells(jnp.asarray(cells))
            return LevelTable(
                states=states,
                values=np.asarray(values),
                remoteness=np.asarray(remoteness),
            )
        manifest = self.load_manifest()
        num = manifest.get("sharded_levels", {}).get(str(level))
        if num is None:
            raise FileNotFoundError(f"no checkpoint for level {level}")
        gs, gc = [], []
        for s in range(num):
            states, cells = self.load_level_shard(level, s, manifest)
            gs.append(states)
            gc.append(cells)
        states = np.concatenate(gs)
        cells = np.concatenate(gc)
        order = np.argsort(states)
        values, remoteness = unpack_cells_np(cells[order])
        return LevelTable(
            states=states[order], values=values, remoteness=remoteness
        )

    def completed_levels(self) -> list[int]:
        manifest = self.load_manifest()
        levels = set(manifest.get("levels", []))
        levels |= {int(k) for k in manifest.get("sharded_levels", {})}
        return sorted(levels)

    # ---------------------------------------------------- dense (per-level)
    # The dense engine's unit of persistence is one level's flat u8 cell
    # array (its entire state — no frontiers exist). The backward sweep
    # chains deepest-first, so only a CONTIGUOUS completed prefix from the
    # top is resumable; the engine computes that prefix itself.

    def save_dense_level(self, level: int, cells) -> None:
        _savez(self.dir / f"dense_{level:04d}.npz",
               cells=np.asarray(cells).reshape(-1))
        manifest = self.load_manifest()
        manifest["dense_levels"] = sorted(
            set(manifest.get("dense_levels", [])) | {level}
        )
        self._write_manifest(manifest)

    def dense_levels(self) -> list:
        return sorted(self.load_manifest().get("dense_levels", []))

    def load_dense_level(self, level: int) -> np.ndarray:
        (cells,) = self._read_npz(self.dir / f"dense_{level:04d}.npz",
                                  ("cells",))
        return cells

    # ------------------------------------------------- sharded (per-shard)
    # One file per (level, shard) and per (frontier snapshot, shard): no
    # global array is ever assembled on one host to WRITE a checkpoint —
    # the single-host-TB bottleneck VERDICT r2 flagged. Multi-host: each
    # process saves only the shards it owns; `finish_*` records the shard
    # count once the set is complete.

    def _shard_level_path(self, level: int, shard: int) -> pathlib.Path:
        return self.dir / f"level_{level:04d}.shard_{shard:04d}.npz"

    def _savez_behind(self, path, **arrays):
        """Write-behind _savez: enqueue the DEFLATE+tmp+os.replace on
        the store's ordered worker and return the WriteTicket (resolved
        to the (raw, stored) byte pair). Arrays are materialized HERE,
        on the calling thread — device downloads must not happen on the
        writer. With write-behind off the write runs inline and the
        ticket is already resolved — callers are agnostic."""
        arrays = {k: np.asarray(v) for k, v in arrays.items()}

        def job(path=path, arrays=arrays):
            return _savez(path, **arrays)

        return self.store.write(job, path=str(path))

    def save_level_shard(self, level: int, shard: int, states, cells):
        """-> WriteTicket resolving to (raw, stored) bytes — the sharded
        engine folds them into its ckpt_bytes_* stats (after the seal
        waits on the ticket) so an operator can see what the
        spill/checkpoint tier costs (and what ``blocks`` compression
        saves) without stat-ing the directory."""
        return self._savez_behind(
            self._shard_level_path(level, shard), states=states, cells=cells
        )

    def finish_level_shards(self, level: int, num_shards: int,
                            ranks=None, drain: bool = True) -> None:
        """Seal one level's shard set. ``drain=False`` is for callers
        that already waited on this level's write tickets (the sharded
        solver's pipelined seals) — a global drain there would stall on
        NEWER levels' queued payloads and collapse the pipeline."""
        if drain:
            self.flush_writes()
        manifest = self.load_manifest()
        manifest.setdefault("sharded_levels", {})[str(level)] = num_shards
        # The sealer (process 0, post-barrier) records every shard file's
        # crc — the files live on the shared checkpoint filesystem, and
        # sealing is the one moment the set is known complete.
        crc = manifest.setdefault("crc", {})
        for s in range(num_shards):
            p = self._shard_level_path(level, s)
            if p.exists():
                crc[p.name] = file_crc32(p)
        self._stamp_seal(manifest, "level_seals", level, ranks)
        self._write_manifest(manifest)
        faults.fire(
            "ckpt.save_level",
            path=str(self._shard_level_path(level, 0)),
            level=level,
        )

    def level_shard_count(self, level: int):
        """Shards the level was saved with, or None if not saved sharded."""
        return self.load_manifest().get("sharded_levels", {}).get(str(level))

    def load_level_shard(self, level: int, shard: int, manifest=None):
        """-> (states, packed cells) of one shard of one level (crc-
        verified; a mismatch quarantines the whole level and raises).
        Callers looping over a level's shards pass one pre-loaded
        ``manifest`` instead of paying a read per shard."""
        path = self._shard_level_path(level, shard)
        try:
            return self._read_npz(path, ("states", "cells"), manifest)
        except CorruptCheckpointError:
            self.quarantine_level(level)
            raise

    def prefetch_level_shards(self, level: int, num_shards: int,
                              manifest=None) -> None:
        """Readahead hint for one sealed level's shard files (the
        solver's level schedule calls this one level AHEAD of the
        backward resolve that will load them)."""
        if manifest is None:
            manifest = self.load_manifest()
        for s in range(num_shards):
            self._hint_npz(self._shard_level_path(level, s),
                           ("states", "cells"), manifest)

    def prefetch_level(self, level: int) -> None:
        """Readahead hint for one sealed GLOBAL level file."""
        path = self._level_path(level)
        if path.exists():
            self._hint_npz(path, ("states", "cells"))

    def lookup_level_state(self, level: int, state):
        """(value, remoteness) of one CANONICAL packed state, served from
        this directory's files — or None when the level/state is absent.

        The big-run query path (SURVEY.md §1: every reachable position is a
        by-product of the solve): with store_tables=False nothing lives in
        host memory, but the checkpoint holds every solved cell. Reads the
        global level file when present; otherwise exactly ONE
        (level, shard) file, chosen by the same owner hash that routed the
        state during the solve — never assembles the level.
        """
        cache = getattr(self, "_lookup_cache", None)
        path = self._level_path(level)
        if path.exists():
            cache_key = (level, None)
        else:
            num = self.level_shard_count(level)
            if num is None:
                return None
            from gamesmanmpi_tpu.core.hashing import owner_shard_np

            shard = int(owner_shard_np(
                np.asarray([state], dtype=np.uint64), num
            )[0])
            cache_key = (level, shard)
        if cache is not None and cache[0] == cache_key:
            states, cells = cache[1]
        elif cache_key[1] is None:
            states, cells = self._read_npz(path, ("states", "cells"))
        else:
            states, cells = self.load_level_shard(level, cache_key[1])
        # Memoize the last-loaded table: a batch of point queries often
        # lands in the same (level, shard), and at big-run scale one shard
        # file is a multi-hundred-MB read.
        self._lookup_cache = (cache_key, (states, cells))
        # Per-shard slices keep the engine's sorted invariant; the global
        # file is sorted by construction. The probe is the shared
        # canonicalize→probe search every query route uses (core/probe.py).
        from gamesmanmpi_tpu.core.probe import probe_sorted_np

        idx, hit = probe_sorted_np(
            states, np.asarray([state], dtype=states.dtype)
        )
        if not hit[0]:
            return None
        values, remoteness = unpack_cells_np(cells[idx[0] : idx[0] + 1])
        return int(values[0]), int(remoteness[0])

    # ------------------------------------------------ edges (per-shard)
    # The sharded engine's forward edge provenance (ISSUE 3): one npz per
    # (level, shard) holding that shard's edge-index row (eidx — each
    # child's unique-index within its owner's next-level slice, in routing
    # order) and its reply-slot map (slot). Sealed with the geometry the
    # backward must validate on resume: shard count, routing capacity
    # (ecap) and slot length (level capacity x max_moves). A level absent
    # here simply falls back to the lookup backward — pre-edge checkpoint
    # directories keep resuming unchanged.

    def _edges_path(self, level: int, shard: int) -> pathlib.Path:
        return self.dir / f"edges_{level:04d}.shard_{shard:04d}.npz"

    def save_edges_shard(self, level: int, shard: int, eidx, slot):
        """-> WriteTicket resolving to (raw, stored) bytes, like
        save_level_shard."""
        return self._savez_behind(
            self._edges_path(level, shard),
            eidx=np.asarray(eidx, dtype=np.int32),
            slot=np.asarray(slot, dtype=np.int32),
        )

    def finish_edges_level(self, level: int, num_shards: int, ecap: int,
                           slot_len: int, ranks=None,
                           drain: bool = True) -> None:
        """Seal one level's edge-shard set (process 0, post-barrier)."""
        if drain:
            self.flush_writes()
        manifest = self.load_manifest()
        manifest.setdefault("edge_levels", {})[str(level)] = {
            "shards": num_shards, "ecap": int(ecap),
            "slot_len": int(slot_len),
        }
        self._stamp_seal(manifest, "edge_seals", level, ranks)
        self._write_manifest(manifest)

    def edge_level_info(self, level: int):
        """{"shards", "ecap", "slot_len"} of a sealed edge level, or None."""
        return self.load_manifest().get("edge_levels", {}).get(str(level))

    def load_edges_shard(self, level: int, shard: int, manifest=None):
        """-> (eidx [S*ecap] int32, slot [cap*M] int32) of one shard.
        Callers looping over a level's shards pass one pre-loaded
        ``manifest`` instead of paying a read per shard."""
        return self._read_npz(self._edges_path(level, shard),
                              ("eidx", "slot"), manifest)

    def prefetch_edges_level(self, level: int, num_shards: int,
                             manifest=None) -> None:
        """Readahead hint for one sealed level's edge-shard files (the
        backward schedule hints level N-1's edges while level N
        resolves — today's synchronous disk-spilled edge loads become
        cache hits). Pass the already-loaded ``manifest``: S redundant
        manifest reads per hinted level on a shared checkpoint
        filesystem would pay back part of the overlap win."""
        if manifest is None:
            manifest = self.load_manifest()
        for s in range(num_shards):
            self._hint_npz(self._edges_path(level, s), ("eidx", "slot"),
                           manifest)

    # Incremental per-(level, shard) forward saves — the sharded analog of
    # save_frontier_level: written as each level is discovered, superseded
    # by the consolidated per-shard snapshot once forward completes (the
    # format load_frontier_shards/load_frontiers already resume from, which
    # also supports shard-count changes), then deleted.

    def save_forward_level_shard(self, level: int, shard: int, states):
        """-> WriteTicket resolving to (raw, stored) bytes, like
        save_level_shard."""
        return self._savez_behind(
            self.dir / f"frontier_{level:04d}.shard_{shard:04d}.npz",
            states=np.asarray(states),
        )

    def finish_forward_level(self, level: int, num_shards: int,
                             ranks=None, drain: bool = True) -> None:
        """Seal one forward level's shard set (process 0, post-barrier —
        same write discipline as finish_level_shards, including the
        per-file crc so a torn per-rank frontier file is caught and
        quarantined on resume rather than silently resuming a holed
        discovery prefix)."""
        if drain:
            self.flush_writes()
        manifest = self.load_manifest()
        manifest.setdefault("forward_level_shards", {})[str(level)] = (
            num_shards
        )
        crc = manifest.setdefault("crc", {})
        for s in range(num_shards):
            p = self.dir / f"frontier_{level:04d}.shard_{s:04d}.npz"
            if p.exists():
                crc[p.name] = file_crc32(p)
        self._stamp_seal(manifest, "forward_seals", level, ranks)
        self._write_manifest(manifest)

    def _quarantine_forward_shard_level(self, level: int,
                                        num_shards: int) -> None:
        """Quarantine one sealed forward level's shard files and unseal
        it together with every deeper forward level (the resume contract
        is contiguous-from-root): the run degrades to the longest
        rank-consistent prefix and re-expands from its deepest level.

        Each dropped level's files are enumerated at ITS OWN sealed
        shard count (``num_shards`` is only the fallback for records
        missing one) — a mid-reshard tree legitimately seals adjacent
        levels at different counts.

        Idempotent and concurrency-tolerant: under multi-process resume
        EVERY rank walks the same torn directory (the resume-digest
        barrier runs before loads, but the tear itself is discovered
        during them), so a peer may rename a file between this rank's
        exists() and rename() — losing that race is fine (the file IS
        quarantined), and the manifest rewrite is atomic with identical
        content on every rank."""
        manifest = self.load_manifest()
        crc = manifest.get("crc", {})
        rec = manifest.get("forward_level_shards", {})
        dropped = [k for k in rec if int(k) >= level]
        for k in dropped:
            sealed_count = int(rec.get(k) or num_shards)
            rec.pop(k, None)
            manifest.get("forward_seals", {}).pop(k, None)
            for s in range(sealed_count):
                p = self.dir / f"frontier_{int(k):04d}.shard_{s:04d}.npz"
                if int(k) == level and p.exists():
                    try:
                        p.rename(p.with_name(p.name + ".corrupt"))
                    except OSError:
                        pass  # a peer rank won the rename race
                crc.pop(p.name, None)
        self._write_manifest(manifest)

    def load_forward_level_shards(self, num_shards: int) -> dict:
        """-> {level: [per-shard arrays at ``num_shards``]} of every
        sealed forward level, a (possibly partial) discovery prefix; {}
        when none exist.

        Elastic resume (ISSUE 13): a level sealed at a DIFFERENT shard
        count re-partitions through the owner hash on load (streamed —
        one sealed shard file decoded at a time through the block
        store), per level, so a mid-reshard tree with mixed counts
        resumes too. With GAMESMAN_RESHARD=0 a mismatched level raises
        :class:`CheckpointGeometryError` naming the sealed vs requested
        geometry (the pre-elastic behavior silently re-ran forward from
        the root — an opaque loss of hours at big-run scale)."""
        manifest = self.load_manifest()
        rec = manifest.get("forward_level_shards", {})
        out: dict = {}
        mismatched = sorted(
            {int(rec[k]) for k in rec if int(rec[k]) != num_shards}
        )
        if mismatched and not reshard_enabled():
            geom = self.sealed_geometry(manifest)
            raise CheckpointGeometryError(
                f"forward checkpoint levels in {self.dir} are sealed at "
                f"shards={mismatched} (epoch {geom['epoch']}) but this "
                f"solve requested shards={num_shards}, and "
                "GAMESMAN_RESHARD=0 pins resume to the sealed geometry"
            )
        # Batched readahead over the WHOLE prefix before the first read:
        # resume loads are the serial head of a solve, and the prefetch
        # pool decodes level j+1's shards while level j's arrays are
        # consumed. Hints follow each level's OWN sealed count.
        for k in sorted(rec, key=int):
            for s in range(int(rec[k])):
                self._hint_npz(
                    self.dir / f"frontier_{int(k):04d}.shard_{s:04d}.npz",
                    ("states",), manifest,
                )
        # Levels in ascending order: the consumer (_forward_fast) resumes
        # only a contiguous-from-root prefix, so a torn level truncates
        # there — everything below it is still a valid (shorter) resume.
        for k in sorted(rec, key=int):
            sealed_count = int(rec[k])

            def _one(s, k=k):
                path = self.dir / (
                    f"frontier_{int(k):04d}.shard_{s:04d}.npz"
                )
                (states,) = self._read_npz(path, ("states",), manifest)
                return states

            try:
                if sealed_count == num_shards:
                    arrs = [_one(s) for s in range(num_shards)]
                else:
                    arrs = [
                        part[0] for part in reshard_shard_stream(
                            _one, sealed_count, num_shards
                        )
                    ]
            except TORN_NPZ_ERRORS:
                # Torn or crc-mismatching per-rank file (a death between
                # unlink and manifest write in an older layout, a
                # mid-resave before _savez became atomic, or a rank's
                # write the filesystem lied about — BadZipFile/short-read
                # OSError/KeyError/CorruptCheckpointError, ADVICE r5):
                # quarantine this level and keep the intact prefix below
                # it — at big-run scale the prefix is hours of
                # re-discovery — and re-run forward from its deepest.
                self._quarantine_forward_shard_level(int(k), sealed_count)
                break
            out[int(k)] = arrs
        return out

    def drop_forward_level_shards(self) -> None:
        """Forward completed and the consolidated snapshot is sealed: the
        incremental files are now redundant on disk (at big-run scale the
        frontier set is the largest artifact — keep exactly one copy)."""
        manifest = self.load_manifest()
        dropped = manifest.pop("forward_level_shards", {})
        # Manifest first, unlinks second: a death in between leaves orphan
        # files (harmless) instead of sealed entries pointing at deleted
        # files (a FileNotFoundError trap for any future loader).
        self._write_manifest(manifest)
        for k in dropped:
            for path in self.dir.glob(
                f"frontier_{int(k):04d}.shard_*.npz"
            ):
                path.unlink(missing_ok=True)

    def save_frontier_shard(self, shard: int, pools):
        """One shard's slice of every frontier level, one file.
        -> WriteTicket (write-behind, like save_level_shard)."""
        arrays = {
            f"level_{k:04d}": np.asarray(v) for k, v in pools.items()
        }
        return self._savez_behind(
            self.dir / f"frontiers.shard_{shard:04d}.npz", **arrays
        )

    def finish_frontier_shards(self, num_shards: int,
                               drain: bool = True) -> None:
        if drain:
            self.flush_writes()
        manifest = self.load_manifest()
        manifest["frontier_shards"] = num_shards
        self._write_manifest(manifest)

    def load_frontier_shards(self, num_shards: int):
        """-> {level: [per-shard arrays at ``num_shards``]} from the
        consolidated per-shard snapshot, or None when no snapshot
        exists (caller falls back to load_frontiers).

        Elastic resume: a snapshot sealed at a different shard count
        re-partitions on load — STREAMED, one sealed shard file (all
        its levels) decoded at a time through the block store, never a
        global frontier assembly (the single-host-TB bottleneck the
        per-shard layout exists to avoid). With GAMESMAN_RESHARD=0 a
        mismatch raises :class:`CheckpointGeometryError` naming both
        geometries."""
        manifest = self.load_manifest()
        sealed_count = manifest.get("frontier_shards")
        if sealed_count is None:
            return None
        sealed_count = int(sealed_count)
        if sealed_count != num_shards and not reshard_enabled():
            geom = self.sealed_geometry(manifest)
            raise CheckpointGeometryError(
                f"frontier snapshot in {self.dir} is sealed at "
                f"shards={sealed_count} (epoch {geom['epoch']}) but "
                f"this solve requested shards={num_shards}, and "
                "GAMESMAN_RESHARD=0 pins resume to the sealed geometry"
            )
        paths = [self.dir / f"frontiers.shard_{s:04d}.npz"
                 for s in range(sealed_count)]
        for path in paths:  # batched readahead before the first read
            self._hint_npz(path, None, manifest)
        if sealed_count == num_shards:
            out: dict = {}
            for s, path in enumerate(paths):
                members = self._read_npz(path, None, manifest)
                for name, arr in members.items():
                    k = int(name.split("_")[1])
                    out.setdefault(k, [None] * num_shards)[s] = arr
            return out
        # Reshard-on-resume: bucket each old shard's per-level rows by
        # the owner hash at the new count, then sort each new shard's
        # concatenated fragments (per-shard sorted is the engine
        # invariant; fragments are disjoint, so the sort is a merge).
        frags: dict = {}
        for path in paths:
            members = self._read_npz(path, None, manifest)
            for name, arr in members.items():
                k = int(name.split("_")[1])
                tgt = frags.setdefault(k, [[] for _ in range(num_shards)])
                for t, (part,) in enumerate(
                    repartition_rows(arr, num_shards)
                ):
                    tgt[t].append(part)
        return {
            k: [np.sort(np.concatenate(f)) for f in per_new]
            for k, per_new in frags.items()
        }

    # ------------------------------------------- disk budget (ISSUE 12)
    # The campaign regime's third failure class is disk exhaustion: at
    # 7x6 scale the checkpoint tree is the largest thing on the volume,
    # and a multi-day run accretes superseded artifacts — quarantined
    # .corrupt files, per-writer .tmp strays from deaths, unsealed shard
    # files resume ignores, and edge shards whose level has already been
    # resolved AND sealed (the backward's structural per-level fallback
    # to the lookup join makes deleting them safe). disk_usage() feeds
    # the gamesman_ckpt_bytes{kind} gauges; gc_superseded() reclaims the
    # superseded classes so ENOSPC becomes pause -> GC -> retry
    # (resilience/campaign.py) instead of a dead campaign.

    #: filename-prefix -> kind for the disk gauges and the GC scan.
    _KIND_PREFIXES = (
        ("level_", "level"),
        ("frontier", "frontier"),  # frontier_*, frontiers.npz, shards
        ("edges_", "edges"),
        ("dense_", "dense"),
    )

    @classmethod
    def artifact_kind(cls, name: str) -> str:
        """Classify one checkpoint-tree filename for the disk gauges:
        ``corrupt`` and ``tmp`` beat the payload prefixes (a quarantined
        level is reclaimable, a sealed one is not)."""
        if name == "manifest.json":
            return "manifest"
        if name.endswith(".corrupt"):
            return "corrupt"
        if ".tmp" in name:
            return "tmp"
        for prefix, kind in cls._KIND_PREFIXES:
            if name.startswith(prefix):
                return kind
        return "other"

    def disk_usage(self, registry=None) -> dict:
        """Bytes on disk per artifact kind, published as the
        ``gamesman_ckpt_bytes{kind=...}`` gauges (every kind is always
        set, so a GC'd kind reads 0 instead of a stale gauge)."""
        usage = {kind: 0 for _, kind in self._KIND_PREFIXES}
        usage.update({"manifest": 0, "corrupt": 0, "tmp": 0, "other": 0})
        try:
            entries = list(os.scandir(self.dir))
        except OSError:
            entries = []
        for entry in entries:
            try:
                if not entry.is_file():
                    continue
                usage[self.artifact_kind(entry.name)] += (
                    entry.stat().st_size
                )
            except OSError:
                continue  # racing unlink (another rank's quarantine)
        if registry is None:
            from gamesmanmpi_tpu.obs import default_registry

            registry = default_registry()
        for kind, nbytes in usage.items():
            registry.gauge(
                "gamesman_ckpt_bytes",
                "checkpoint-tree bytes on disk by artifact kind",
                kind=kind,
            ).set(float(nbytes))
        return usage

    def quarantine_inventory(self) -> list:
        """[{"file", "bytes"}] of quarantined ``.corrupt`` artifacts —
        the campaign's diagnosis bundle snapshots this BEFORE a GC
        deletes the evidence."""
        out = []
        for p in sorted(self.dir.glob("*.corrupt")):
            try:
                out.append({"file": p.name, "bytes": p.stat().st_size})
            except OSError:
                continue
        return out

    def referenced_files(self, manifest=None) -> set:
        """Filenames the manifest currently seals (the NOT-superseded
        set). Anything else matching an artifact prefix is a stray a
        death left behind — resume already ignores it on disk, GC may
        reclaim it."""
        if manifest is None:
            manifest = self.load_manifest()
        ref = {"manifest.json"}
        for k in manifest.get("levels", []):
            ref.add(f"level_{int(k):04d}.npz")
        for k, num in manifest.get("sharded_levels", {}).items():
            for s in range(int(num)):
                ref.add(f"level_{int(k):04d}.shard_{s:04d}.npz")
        for k in manifest.get("forward_levels", []):
            ref.add(f"frontier_{int(k):04d}.npz")
        for k, num in manifest.get("forward_level_shards", {}).items():
            for s in range(int(num)):
                ref.add(f"frontier_{int(k):04d}.shard_{s:04d}.npz")
        if manifest.get("frontiers"):
            ref.add("frontiers.npz")
        for s in range(int(manifest.get("frontier_shards") or 0)):
            ref.add(f"frontiers.shard_{s:04d}.npz")
        for k, info in manifest.get("edge_levels", {}).items():
            for s in range(int(info.get("shards", 0))):
                ref.add(f"edges_{int(k):04d}.shard_{s:04d}.npz")
        for k in manifest.get("dense_levels", []):
            ref.add(f"dense_{int(k):04d}.npz")
        return ref

    def gc_superseded(self, logger=None, registry=None) -> dict:
        """Reclaim superseded checkpoint artifacts; -> {"files",
        "bytes", "kinds": {kind: bytes}}.

        Reclaimed classes, in order:

        * **consumed edges** — edge shards of levels sealed solved: the
          backward that needed them already ran, and a future resume of
          a re-quarantined level falls back to the lookup join (the
          structural per-level fallback), so these are pure cache. The
          manifest unseals them FIRST, files unlink second — a death in
          between leaves orphans the next GC collects, never sealed
          entries pointing at deleted files;
        * **quarantine** — ``.corrupt`` files (superseded the moment the
          level re-sealed over them; snapshot quarantine_inventory()
          first if the forensics matter);
        * **tmp strays** — dead writers' per-pid temp files;
        * **unreferenced artifacts** — level/frontier/edge/dense files
          the manifest does not seal (unsealed write-behind strays,
          post-consolidation orphans).

        Contract: a QUIESCENT tree — call between attempts (the
        campaign supervisor's use) or from the solve thread of the only
        live solver. The write-behind queue is drained first so an
        in-flight payload whose seal has not run yet is never read as a
        stray mid-write (the store-ticket/seal-ordering invariant).
        """
        self.flush_writes()
        manifest = self.load_manifest()
        solved = set(int(k) for k in manifest.get("levels", []))
        solved |= {int(k) for k in manifest.get("sharded_levels", {})}
        consumed = {
            k: int(info.get("shards", 0))
            for k, info in manifest.get("edge_levels", {}).items()
            if int(k) in solved
        }
        if consumed:
            for k in consumed:
                manifest.get("edge_levels", {}).pop(k, None)
                manifest.get("edge_seals", {}).pop(k, None)
            self._write_manifest(manifest)
        freed = {"files": 0, "bytes": 0, "kinds": {}}

        def reclaim(path: pathlib.Path, kind: str) -> None:
            try:
                nbytes = path.stat().st_size
                path.unlink()
            except OSError:
                return  # racing unlink / already gone
            freed["files"] += 1
            freed["bytes"] += nbytes
            freed["kinds"][kind] = freed["kinds"].get(kind, 0) + nbytes

        for k, shards in consumed.items():
            for s in range(shards):
                reclaim(self._edges_path(int(k), s), "edges")
        referenced = self.referenced_files(manifest)
        for p in sorted(self.dir.iterdir()):
            if not p.is_file() or p.name in referenced:
                continue
            kind = self.artifact_kind(p.name)
            if kind != "other":  # unknown files are never GC fodder
                reclaim(p, kind)
        if registry is None:
            from gamesmanmpi_tpu.obs import default_registry

            registry = default_registry()
        registry.counter(
            "gamesman_ckpt_gc_reclaimed_bytes_total",
            "checkpoint bytes reclaimed by retention GC",
        ).inc(float(freed["bytes"]))
        if logger is not None:
            logger.log({"phase": "ckpt_gc", **{
                k: v for k, v in freed.items() if k != "kinds"
            }, "kinds": dict(freed["kinds"])})
        self.disk_usage(registry=registry)  # refresh the gauges post-GC
        return freed

    # Forward-phase snapshot: all per-level frontiers after discovery, so a
    # restarted solve skips the whole forward sweep (restart-from-level,
    # SURVEY.md §5.4 — the backward phase then loads completed levels).
    #
    # Two granularities. The original all-at-once snapshot (save_frontiers)
    # only helps once forward COMPLETES; at big-board scale forward alone is
    # a multi-hour phase, longer than this environment's observed relay MTBF
    # (docs/ARCHITECTURE.md "6x6 single-chip feasibility"), so the fast-path
    # engine saves each level INCREMENTALLY as it is discovered
    # (save_frontier_level) and marks completion with a manifest flag — same
    # total bytes as the end snapshot, but a mid-forward death keeps the
    # discovered prefix and the next run resumes expansion from the deepest
    # saved level instead of restarting discovery from the root.

    def save_frontier_level(self, level: int, states) -> None:
        """One discovered level's frontier, saved the moment its count is
        known. The manifest records the level only after the file is fully
        written, so a death mid-write never yields a listed-but-corrupt
        entry (same discipline as save_level)."""
        path = self.dir / f"frontier_{level:04d}.npz"
        _savez(path, states=np.asarray(states))
        manifest = self.load_manifest()
        manifest["forward_levels"] = sorted(
            set(manifest.get("forward_levels", [])) | {level}
        )
        manifest.setdefault("crc", {})[path.name] = file_crc32(path)
        self._write_manifest(manifest)
        faults.fire("ckpt.save_frontier", path=str(path), level=level)

    def load_forward_levels(self) -> dict:
        """-> {level: sorted packed states} saved incrementally during a
        (possibly interrupted) forward sweep; {} when none exist. A
        torn or crc-mismatching level quarantines there and keeps the
        intact prefix below it (re-expansion resumes from its deepest),
        exactly like the sharded loader's torn-directory handling."""
        out = {}
        manifest = self.load_manifest()
        ks = sorted(manifest.get("forward_levels", []), key=int)
        for k in ks:  # batched readahead before the first read
            self._hint_npz(self.dir / f"frontier_{int(k):04d}.npz",
                           ("states",), manifest)
        for k in ks:
            path = self.dir / f"frontier_{int(k):04d}.npz"
            try:
                (out[int(k)],) = self._read_npz(path, ("states",),
                                                manifest)
            except TORN_NPZ_ERRORS:
                out.pop(int(k), None)
                self._quarantine_frontier(int(k))
                break
        return out

    def mark_frontiers_complete(self) -> None:
        """Forward discovery finished; every level is on disk via
        save_frontier_level. load_frontiers then serves resumes from the
        per-level files — no end-of-forward re-snapshot."""
        manifest = self.load_manifest()
        manifest["frontiers_complete"] = True
        self._write_manifest(manifest)

    def save_frontiers(self, pools) -> None:
        # Frontiers keep the game's state dtype (uint32 games stay uint32 —
        # at north-star scale the snapshot is the biggest file on disk).
        arrays = {
            f"level_{k:04d}": np.asarray(v) for k, v in pools.items()
        }
        path = self.dir / "frontiers.npz"
        _savez(path, **arrays)
        manifest = self.load_manifest()
        manifest["frontiers"] = True
        manifest.setdefault("crc", {})[path.name] = file_crc32(path)
        self._write_manifest(manifest)
        faults.fire("ckpt.save_frontier", path=str(path))

    def load_frontiers(self):
        """-> {level: sorted packed states} or None if no snapshot exists.

        Reads the global snapshot, or assembles one from per-shard snapshot
        files (a sharded run's checkpoint resumed at a different shard
        count, or by the single-device solver).
        """
        manifest = self.load_manifest()
        if manifest.get("frontiers"):
            path = self.dir / "frontiers.npz"
            if path.exists():
                try:
                    members = self._read_npz(path, None, manifest)
                    return {
                        int(name.split("_")[1]): arr
                        for name, arr in members.items()
                    }
                except TORN_NPZ_ERRORS:
                    # Corrupt global snapshot: quarantine it and fall
                    # through to the other resume sources (or a fresh
                    # forward) instead of dying on resume.
                    path.rename(path.with_name(path.name + ".corrupt"))
                    manifest.pop("frontiers", None)
                    manifest.get("crc", {}).pop(path.name, None)
                    self._write_manifest(manifest)
        if manifest.get("frontiers_complete"):
            out = self.load_forward_levels()
            if self.load_manifest().get("frontiers_complete"):
                return out
            # A frontier level quarantined mid-load: the snapshot is no
            # longer complete — resume as a partial forward instead
            # (load_forward_levels serves the intact prefix).
            return None
        num = manifest.get("frontier_shards")
        if num is None:
            return None
        shards = self.load_frontier_shards(num)
        return {
            k: np.sort(np.concatenate(arrs)) for k, arrs in shards.items()
        }


def save_table_npz(path: str, table: dict) -> None:
    """Dump a host-solve table ({pos: (value, remoteness)}) as one .npz.

    Always PLAIN npz (allow_block_framing=False): ``--table-out`` output
    is a user-facing artifact read by plain np.load downstream — the
    checkpoint compression knob must not reshape it.
    """
    states = np.array(sorted(table), dtype=np.uint64)
    values = jnp.asarray(
        np.array([table[int(s)][0] for s in states], dtype=np.uint8)
    )
    rems = jnp.asarray(
        np.array([table[int(s)][1] for s in states], dtype=np.int32)
    )
    _savez(
        path, allow_block_framing=False,
        states=states, cells=np.asarray(pack_cells(values, rems)),
    )


def save_result_npz(path: str, result) -> None:
    """Dump a SolveResult's full table as one .npz (packed cells per
    level). Plain npz always — see save_table_npz."""
    arrays = {}
    for level, table in result.levels.items():
        cells = np.asarray(
            pack_cells(jnp.asarray(table.values), jnp.asarray(table.remoteness))
        )
        arrays[f"states_{level:04d}"] = table.states
        arrays[f"cells_{level:04d}"] = cells
    _savez(path, allow_block_framing=False, **arrays)
