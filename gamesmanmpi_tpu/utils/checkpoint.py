"""Per-level checkpoint / resume (SURVEY.md §5.4).

The reference has no checkpointing — a solve is monolithic and in-memory.
For the north-star scale (4.5e12 states on a preemptible pod) restart-from-
level recovery is required. The unit of persistence is the natural unit of
the level-synchronous engine: one solved level = (sorted states, packed
value+remoteness cells via core.codec). Plain .npz per level plus a JSON
manifest — no framework dependency, shard-friendly, and the packed cell
format is exactly the HBM table layout.
"""

from __future__ import annotations

import json
import pathlib

import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.core.codec import pack_cells, unpack_cells


class LevelCheckpointer:
    """Saves solved levels as they complete; loads them for resume."""

    def __init__(self, directory: str):
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.manifest_path = self.dir / "manifest.json"

    def _level_path(self, level: int) -> pathlib.Path:
        return self.dir / f"level_{level:04d}.npz"

    def bind_game(self, name: str) -> None:
        """Record/validate which game this directory belongs to.

        Game names encode every parameter (board, symmetry flag, ...), so a
        resume with a different spec — e.g. sym=1 against a sym=0 checkpoint,
        whose canonical tables would silently disagree — fails loudly here
        instead of mixing tables. Engines call this before loading anything.
        """
        manifest = self.load_manifest()
        bound = manifest.get("game")
        if bound is None:
            manifest["game"] = name
            self.manifest_path.write_text(json.dumps(manifest))
        elif bound != name:
            raise ValueError(
                f"checkpoint directory {self.dir} belongs to game {bound!r}, "
                f"not {name!r} — use a fresh --checkpoint-dir"
            )

    def save_level(self, level: int, table) -> None:
        cells = np.asarray(
            pack_cells(jnp.asarray(table.values), jnp.asarray(table.remoteness))
        )
        np.savez_compressed(
            self._level_path(level), states=table.states, cells=cells
        )
        manifest = self.load_manifest()
        manifest["levels"] = sorted(set(manifest.get("levels", [])) | {level})
        self.manifest_path.write_text(json.dumps(manifest))

    def load_manifest(self) -> dict:
        if self.manifest_path.exists():
            return json.loads(self.manifest_path.read_text())
        return {}

    def load_level(self, level: int):
        from gamesmanmpi_tpu.solve.engine import LevelTable

        with np.load(self._level_path(level)) as z:
            states = z["states"]
            values, remoteness = unpack_cells(jnp.asarray(z["cells"]))
        return LevelTable(
            states=states,
            values=np.asarray(values),
            remoteness=np.asarray(remoteness),
        )

    def completed_levels(self) -> list[int]:
        return list(self.load_manifest().get("levels", []))

    # Forward-phase snapshot: all per-level frontiers after discovery, so a
    # restarted solve skips the whole forward sweep (restart-from-level,
    # SURVEY.md §5.4 — the backward phase then loads completed levels).

    def save_frontiers(self, pools) -> None:
        # Frontiers keep the game's state dtype (uint32 games stay uint32 —
        # at north-star scale the snapshot is the biggest file on disk).
        arrays = {
            f"level_{k:04d}": np.asarray(v) for k, v in pools.items()
        }
        np.savez_compressed(self.dir / "frontiers.npz", **arrays)
        manifest = self.load_manifest()
        manifest["frontiers"] = True
        self.manifest_path.write_text(json.dumps(manifest))

    def load_frontiers(self):
        """-> {level: sorted packed states} or None if no snapshot exists."""
        if not self.load_manifest().get("frontiers"):
            return None
        path = self.dir / "frontiers.npz"
        if not path.exists():
            return None
        out = {}
        with np.load(path) as z:
            for name in z.files:
                out[int(name.split("_")[1])] = z[name]
        return out


def save_table_npz(path: str, table: dict) -> None:
    """Dump a host-solve table ({pos: (value, remoteness)}) as one .npz."""
    states = np.array(sorted(table), dtype=np.uint64)
    values = jnp.asarray(
        np.array([table[int(s)][0] for s in states], dtype=np.uint8)
    )
    rems = jnp.asarray(
        np.array([table[int(s)][1] for s in states], dtype=np.int32)
    )
    np.savez_compressed(
        path, states=states, cells=np.asarray(pack_cells(values, rems))
    )


def save_result_npz(path: str, result) -> None:
    """Dump a SolveResult's full table as one .npz (packed cells per level)."""
    arrays = {}
    for level, table in result.levels.items():
        cells = np.asarray(
            pack_cells(jnp.asarray(table.values), jnp.asarray(table.remoteness))
        )
        arrays[f"states_{level:04d}"] = table.states
        arrays[f"cells_{level:04d}"] = cells
    np.savez_compressed(path, **arrays)
