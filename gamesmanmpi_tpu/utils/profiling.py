"""Profiler capture (SURVEY.md §5.1).

Reference: none beyond an elapsed-time print. Rebuild: wrap any solve in a
jax.profiler trace (viewable in TensorBoard/Perfetto) with a no-op fallback
when no directory is given.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def maybe_profile(trace_dir=None):
    """Context manager: jax.profiler.trace(trace_dir) when a dir is given."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(str(trace_dir)):
        yield
