"""Database integrity validation (CI-runnable, see tools/check_db.py).

Structural checks only — no game construction, no kernels, no backend
initialization (the package root's `import jax` runs, but nothing here
touches a device) — so the checker runs in seconds even where backend
bring-up is expensive or wedged, and a corrupted DB is caught before a
serving process ever mmaps it:

* manifest parses, format/version/fields are right (db/format.read_manifest)
* every level's shard files exist and match their sha256 checksums
* keys are strictly ascending (sorted + unique, the probe's contract),
  hold no padding sentinel, and match the manifest dtype and count
* cells are uint32, parallel to the keys, and every cell decodes to a
  DECIDED value (an UNDECIDED cell in a solved DB is a solver bug —
  lookups would report found-but-valueless)
* **format v2** levels additionally prove the block machinery: index
  structure vs real stream bytes, per-block crc32 + decoded position
  counts, manifest first_keys vs the decoded blocks — checked
  block-by-block in O(one block) memory (the same invariant set as v1;
  the storage changed, the contract did not, and the gate must run on
  replica nodes sized for the compressed artifact)
* a manifest-recorded opening book (``book.gmb``) exists, matches its
  sha256 seal, parses, and holds sorted-unique decided entries — the
  structural half only; the answer-level re-probe (every entry vs the
  reader's slow path) needs game kernels and lives in
  db/book.py ``verify_book``, which tools/check_db.py runs

``db_stats`` folds the per-level size/ratio table (tools/check_db.py,
bench BENCH_DB_COMPRESS); ``db_equal`` proves two DBs logically
identical across storage versions (the compressed-migration gate);
``db_equal_fast`` is its O(manifest) digest screen — same sealed
sha256s means same content with zero decode, anything else falls back
to the streamed compare.
"""

from __future__ import annotations

import pathlib

import numpy as np

from gamesmanmpi_tpu.utils.env import env_bool

from gamesmanmpi_tpu.compress import (
    BlockCorruptError,
    block_bounds,
    decode_block,
    index_offsets,
    num_blocks,
    validate_index,
)  # block-streamed v2 checks: O(one block) memory at any DB scale
from gamesmanmpi_tpu.core.bitops import sentinel_for
from gamesmanmpi_tpu.core.codec import unpack_cells_np
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.db.format import (
    DbFormatError,
    file_sha256,
    level_is_blocked,
    read_manifest,
)


def check_db(directory, verbose=None) -> list[str]:
    """Validate one DB directory; returns a list of problems (empty = OK).

    verbose: optional callable taking one progress line per level.
    """
    directory = pathlib.Path(directory)
    problems: list[str] = []
    try:
        manifest = read_manifest(directory)
    except DbFormatError as e:
        return [str(e)]
    try:
        dt = np.dtype(manifest["state_dtype"])
        sentinel = sentinel_for(dt)
    except TypeError as e:
        return [f"manifest state_dtype: {e}"]
    total = 0
    for key in sorted(manifest["levels"], key=int):
        rec = manifest["levels"][key]
        tag = f"level {key}"
        ok = True
        for kind in ("keys", "cells"):
            path = directory / rec[kind]
            if not path.exists():
                problems.append(f"{tag}: missing file {rec[kind]}")
                ok = False
                continue
            digest = file_sha256(path)
            if digest != rec[f"{kind}_sha256"]:
                problems.append(
                    f"{tag}: {kind} checksum mismatch "
                    f"({digest[:12]}… != {rec[f'{kind}_sha256'][:12]}…)"
                )
                ok = False
        if not ok:
            continue
        if level_is_blocked(rec):
            n = _check_blocked_level(
                directory, rec, dt, sentinel, tag, problems
            )
            if n is not None:
                total += n
                if verbose is not None:
                    verbose(f"{tag}: {n} positions OK (blocked)")
            continue
        # The integrity gate must see exactly what is on disk, never a
        # cached decode, so it bypasses the block store by design.
        # store-io: integrity gate reads raw payload bytes on purpose
        keys = np.load(directory / rec["keys"], mmap_mode="r")
        cells = np.load(directory / rec["cells"], mmap_mode="r")  # store-io: raw gate read
        if keys.dtype != dt:
            problems.append(
                f"{tag}: keys dtype {keys.dtype}, manifest says {dt}"
            )
            continue
        if keys.shape[0] != rec["count"]:
            problems.append(
                f"{tag}: {keys.shape[0]} keys, manifest says {rec['count']}"
            )
        if cells.dtype != np.uint32 or cells.shape != keys.shape:
            problems.append(
                f"{tag}: cells are {cells.dtype}{list(cells.shape)}, "
                f"expected uint32[{keys.shape[0]}]"
            )
            continue
        if keys.shape[0]:
            if not np.all(keys[1:] > keys[:-1]):
                problems.append(f"{tag}: keys not strictly ascending")
            if keys[-1] == sentinel:
                problems.append(f"{tag}: keys contain the padding sentinel")
        # Decode through the one codec (not a private mask copy): a cell
        # layout change must not silently let the gate validate old bits.
        cell_values, _ = unpack_cells_np(np.asarray(cells))
        undecided = int(np.count_nonzero(cell_values == UNDECIDED))
        if undecided:
            problems.append(f"{tag}: {undecided} UNDECIDED cells")
        total += int(keys.shape[0])
        if verbose is not None:
            verbose(f"{tag}: {keys.shape[0]} positions OK")
    declared = manifest.get("num_positions")
    if declared is not None and declared != total:
        problems.append(
            f"manifest num_positions {declared} != shard total {total}"
        )
    problems += _check_book(directory, manifest, verbose)
    return problems


def _check_book(directory, manifest, verbose=None) -> list[str]:
    """Structural opening-book check — still game-free: seal (sha256),
    magic/header parse, entry count vs manifest, sorted-unique
    positions, decided cells. OpeningBook.load does the seal+parse
    (raising DbFormatError exactly like a worker warm start would)."""
    rec = manifest.get("book")
    if not rec:
        return []
    from gamesmanmpi_tpu.db.book import OpeningBook
    try:
        book = OpeningBook.load(directory, manifest, verify=True)
    except (DbFormatError, KeyError, ValueError, OSError) as e:
        return [f"book: {e}"]
    problems: list[str] = []
    if len(book) != int(rec.get("count", -1)):
        problems.append(
            f"book: {len(book)} entries, manifest says {rec.get('count')}"
        )
    pos = np.asarray(book.positions)
    if pos.size and not np.all(pos[1:] > pos[:-1]):
        problems.append("book: positions not strictly ascending")
    values, _ = unpack_cells_np(np.asarray(book.cells))
    undecided = int(np.count_nonzero(values == UNDECIDED))
    if undecided:
        problems.append(f"book: {undecided} UNDECIDED entries")
    if verbose is not None and not problems:
        verbose(f"book: {len(book)} entries OK (plies {rec.get('plies')})")
    return problems


def _check_blocked_level(directory, rec, dt, sentinel, tag, problems):
    """Validate one v2 level block-by-block in O(one block) memory —
    the gate runs on replica nodes sized for the COMPRESSED artifact,
    so materializing a decoded multi-GB level (as a naive decode-all
    would) could OOM exactly where this check matters most.

    Per block: crc32 + codec decode + count (decode_block), dtype,
    in-block strict ascent, cross-boundary ascent against the previous
    block's last key, the manifest's first_keys router entry, cells
    parallel/uint32/decided. Plus the structural whole-level checks:
    index-vs-file sizes, keys-vs-cells counts, manifest count and
    stored_bytes. Returns the verified position count, or None after
    appending problems (one per level is enough: the first corrupt
    block ends the level's scan)."""
    kindex, cindex = rec.get("keys_blocks"), rec.get("cells_blocks")
    kpath, cpath = directory / rec["keys"], directory / rec["cells"]
    try:
        validate_index(kindex, stream_bytes=kpath.stat().st_size)
        validate_index(cindex, stream_bytes=cpath.stat().st_size)
    except (BlockCorruptError, OSError, TypeError) as e:
        problems.append(f"{tag}: block index invalid: {e}")
        return None
    if int(kindex["count"]) != int(cindex["count"]):
        problems.append(
            f"{tag}: {kindex['count']} keys vs {cindex['count']} cells "
            "in the block index"
        )
        return None
    if int(kindex["count"]) != int(rec["count"]):
        problems.append(
            f"{tag}: block index holds {kindex['count']} positions, "
            f"manifest says {rec['count']}"
        )
        return None
    first = [int(k) for k in rec.get("first_keys", [])]
    if len(first) != num_blocks(kindex):
        problems.append(
            f"{tag}: {len(first)} first_keys for "
            f"{num_blocks(kindex)} blocks"
        )
        return None
    stored = kpath.stat().st_size + cpath.stat().st_size
    if "stored_bytes" in rec and int(rec["stored_bytes"]) != stored:
        problems.append(
            f"{tag}: stored_bytes {rec['stored_bytes']} != {stored}"
        )
    koffs, coffs = index_offsets(kindex), index_offsets(cindex)
    prev_last = None
    total = 0
    undecided = 0
    try:
        # store-io: block-by-block gate reads the raw streams on purpose
        with open(kpath, "rb") as kf, open(cpath, "rb") as cf:
            for b in range(num_blocks(kindex)):
                keys, cells = _read_block_pair(
                    kf, cf, kindex, cindex, koffs, coffs, b
                )
                if keys.dtype != dt:
                    problems.append(
                        f"{tag}: keys dtype {keys.dtype}, manifest "
                        f"says {dt}"
                    )
                    return None
                if cells.dtype != np.uint32 or cells.shape != keys.shape:
                    problems.append(
                        f"{tag}: block {b} cells are "
                        f"{cells.dtype}{list(cells.shape)}, expected "
                        f"uint32[{keys.shape[0]}]"
                    )
                    return None
                if keys.shape[0]:
                    if int(keys[0]) != first[b]:
                        problems.append(
                            f"{tag}: manifest first_keys disagree with "
                            "the decoded blocks — the probe's block "
                            "router would misroute"
                        )
                        return None
                    if not np.all(keys[1:] > keys[:-1]) or (
                        prev_last is not None and not keys[0] > prev_last
                    ):
                        problems.append(
                            f"{tag}: keys not strictly ascending "
                            f"(block {b})"
                        )
                        return None
                    prev_last = keys[-1]
                cell_values, _ = unpack_cells_np(cells)
                undecided += int(
                    np.count_nonzero(cell_values == UNDECIDED)
                )
                total += int(keys.shape[0])
    except (BlockCorruptError, OSError) as e:
        problems.append(f"{tag}: block stream invalid: {e}")
        return None
    if prev_last is not None and prev_last == sentinel:
        problems.append(f"{tag}: keys contain the padding sentinel")
    if undecided:
        problems.append(f"{tag}: {undecided} UNDECIDED cells")
    return total


def db_stats(directory) -> dict:
    """Per-level size/ratio summary of a (valid) DB directory, shared by
    the tools/check_db.py table, bench.py's BENCH_DB_COMPRESS gate, and
    the serving docs' shipping math. Raises DbFormatError on an
    unreadable manifest; file-size figures come from disk, ratios from
    the v2 manifest records (v1 levels report ratio 1.0).

    -> {"version", "num_positions", "raw_bytes", "stored_bytes",
        "ratio", "levels": [{level, count, raw_bytes, stored_bytes,
        ratio, codecs}]}
    """
    directory = pathlib.Path(directory)
    manifest = read_manifest(directory)
    rows = []
    for key in sorted(manifest["levels"], key=int):
        rec = manifest["levels"][key]
        if level_is_blocked(rec):
            # raw/stored_bytes are optional in the record (check_db
            # treats them as such — a foreign writer may omit them);
            # fall back to disk sizes / the dtype arithmetic instead of
            # KeyError-ing after a clean check.
            stored = int(rec.get("stored_bytes", sum(
                (directory / rec[kind]).stat().st_size
                for kind in ("keys", "cells")
                if (directory / rec[kind]).exists()
            )))
            raw = int(rec.get("raw_bytes", int(rec["count"]) * (
                np.dtype(manifest["state_dtype"]).itemsize + 4
            )))
            codecs = sorted(
                set(rec["keys_blocks"]["codecs"])
                | set(rec["cells_blocks"]["codecs"])
            )
        else:
            stored = raw = sum(
                (directory / rec[kind]).stat().st_size
                for kind in ("keys", "cells")
                if (directory / rec[kind]).exists()
            )
            codecs = ["none"]
        rows.append({
            "level": int(key),
            "count": int(rec["count"]),
            "raw_bytes": raw,
            "stored_bytes": stored,
            "ratio": raw / stored if stored else 1.0,
            "codecs": codecs,
        })
    raw = sum(r["raw_bytes"] for r in rows)
    stored = sum(r["stored_bytes"] for r in rows)
    return {
        "version": int(manifest["version"]),
        "num_positions": sum(r["count"] for r in rows),
        "raw_bytes": raw,
        "stored_bytes": stored,
        "ratio": raw / stored if stored else 1.0,
        "levels": rows,
    }


class _LevelRangeReader:
    """Uniform `[lo, hi)` access to one level's (keys, cells) across
    storage versions: v1 slices the mmap (no copy), v2 decodes only the
    blocks covering the range. Lets db_equal stream a comparison in
    O(chunk) memory instead of materializing multi-GB decoded levels."""

    def __init__(self, directory, rec):
        self.count = int(rec["count"])
        self._blocked = level_is_blocked(rec)
        if self._blocked:
            self._kindex = rec["keys_blocks"]
            self._cindex = rec["cells_blocks"]
            validate_index(
                self._kindex,
                stream_bytes=(directory / rec["keys"]).stat().st_size,
            )
            validate_index(
                self._cindex,
                stream_bytes=(directory / rec["cells"]).stat().st_size,
            )
            self._koffs = index_offsets(self._kindex)
            self._coffs = index_offsets(self._cindex)
            self._kf = self._cf = None
            try:
                # The equality verdict must not share a cache with the
                # readers it is auditing.
                # store-io: streaming compare reads raw bytes on purpose
                self._kf = open(directory / rec["keys"], "rb")
                self._cf = open(directory / rec["cells"], "rb")  # store-io: raw gate read
            except BaseException:
                # A half-built reader is never returned to the caller's
                # close() bookkeeping — release what DID open.
                self.close()
                raise
        else:
            # store-io: raw gate read (see above)
            self._keys = np.load(directory / rec["keys"], mmap_mode="r")  # store-io: raw gate read
            self._cells = np.load(directory / rec["cells"], mmap_mode="r")  # store-io: raw gate read

    def _block(self, b):
        return _read_block_pair(
            self._kf, self._cf, self._kindex, self._cindex,
            self._koffs, self._coffs, b,
        )

    def range(self, lo, hi):
        """-> (keys[lo:hi], cells[lo:hi])."""
        if not self._blocked:
            return self._keys[lo:hi], self._cells[lo:hi]
        bp = int(self._kindex["block_positions"])
        ks, cs = [], []
        for b in range(lo // bp, (max(hi, lo + 1) - 1) // bp + 1):
            keys, cells = self._block(b)
            start, _ = block_bounds(self._kindex, b)
            a = max(lo - start, 0)
            z = min(hi - start, keys.shape[0])
            ks.append(keys[a:z])
            cs.append(cells[a:z])
        return np.concatenate(ks), np.concatenate(cs)

    def close(self):
        if self._blocked:
            for fh in (self._kf, self._cf):
                if fh is not None:
                    fh.close()
            self._kf = self._cf = None


def _read_block_pair(kf, cf, kindex, cindex, koffs, coffs, b):
    """Seek+read+decode block b of a (keys, cells) .gmb stream pair —
    the one block-stream access sequence both the streaming checker and
    _LevelRangeReader share."""
    kf.seek(int(koffs[b]))
    keys = decode_block(kindex, b, kf.read(int(koffs[b + 1] - koffs[b])))
    cf.seek(int(coffs[b]))
    cells = decode_block(cindex, b, cf.read(int(coffs[b + 1] - coffs[b])))
    return keys, cells


def db_equal(dir_a, dir_b) -> list[str]:
    """Logical equality of two DBs' solved content — same games, levels,
    keys, and cells, regardless of storage version. Returns differences
    (empty = identical); the migration gate that proves a compressed
    re-export answers every position identically to its v1 twin without
    sampling."""
    dir_a, dir_b = pathlib.Path(dir_a), pathlib.Path(dir_b)
    try:
        ma, mb = read_manifest(dir_a), read_manifest(dir_b)
    except DbFormatError as e:
        return [str(e)]
    diffs = []
    # spec_sha256 is the gamedsl rules hash: absent on both sides for
    # registry games (None == None), it only gates compiled-spec DBs —
    # where a rules change must fail --same-as even before the tables
    # are compared.
    for field in ("game", "spec", "state_dtype", "sym", "spec_sha256"):
        if ma.get(field) != mb.get(field):
            diffs.append(
                f"{field}: {ma.get(field)!r} != {mb.get(field)!r}"
            )
    la, lb = set(ma["levels"]), set(mb["levels"])
    for missing in sorted(la ^ lb, key=int):
        diffs.append(f"level {missing}: present in only one DB")
    if diffs:
        return diffs
    # Chunked comparison (multiple of the default block size, so v2
    # sides decode each block once): O(chunk) memory at any DB scale.
    chunk = 1 << 20
    for key in sorted(la, key=int):
        readers = []
        try:
            try:
                ra = _LevelRangeReader(dir_a, ma["levels"][key])
                readers.append(ra)
                rb = _LevelRangeReader(dir_b, mb["levels"][key])
                readers.append(rb)
            except (BlockCorruptError, OSError, KeyError) as e:
                diffs.append(f"level {key}: unreadable: {e}")
                continue
            if ra.count != rb.count:
                diffs.append(
                    f"level {key}: {ra.count} vs {rb.count} positions"
                )
                continue
            for lo in range(0, max(ra.count, 1), chunk):
                hi = min(lo + chunk, ra.count)
                if hi <= lo:
                    break
                try:
                    ka, ca = ra.range(lo, hi)
                    kb, cb = rb.range(lo, hi)
                except (BlockCorruptError, OSError) as e:
                    diffs.append(f"level {key}: unreadable: {e}")
                    break
                if not np.array_equal(ka, kb):
                    diffs.append(f"level {key}: keys differ")
                    break
                if not np.array_equal(np.asarray(ca), np.asarray(cb)):
                    diffs.append(f"level {key}: cells differ")
                    break
        finally:
            for r in readers:
                r.close()
    return diffs


def db_equal_fast(dir_a, dir_b):
    """O(manifest) equality screen: compare the two DBs' sealed
    per-level sha256 digests (plus identity fields, level sets, counts
    and v2 block routing) without decoding a single payload byte.

    -> ``(verdict, diffs)`` where verdict is

    * ``"same"`` — identity fields, level structure, and every sealed
      digest match: the stored bytes are identical, so the solved
      content is too;
    * ``"different"`` — the manifests disagree on identity, levels, or
      counts: no decode can reconcile that;
    * ``"unknown"`` — digests differ (or the sides use different
      storage versions / codecs). Digest inequality is NOT a logical
      verdict — the same solved table stored v1 vs v2 hashes
      differently — so callers needing an answer fall back to the full
      streamed :func:`db_equal` (tools/check_db.py ``--same-as`` does
      exactly that; ``--deep`` skips the screen).

    ``diffs`` names what disagreed (empty for ``"same"``).
    """
    dir_a, dir_b = pathlib.Path(dir_a), pathlib.Path(dir_b)
    try:
        ma, mb = read_manifest(dir_a), read_manifest(dir_b)
    except DbFormatError as e:
        return "different", [str(e)]
    diffs = []
    for field in ("game", "spec", "state_dtype", "sym", "spec_sha256"):
        if ma.get(field) != mb.get(field):
            diffs.append(f"{field}: {ma.get(field)!r} != {mb.get(field)!r}")
    la, lb = set(ma["levels"]), set(mb["levels"])
    for missing in sorted(la ^ lb, key=int):
        diffs.append(f"level {missing}: present in only one DB")
    if diffs:
        return "different", diffs
    needs_deep = []
    for key in sorted(la, key=int):
        ra, rb = ma["levels"][key], mb["levels"][key]
        if int(ra["count"]) != int(rb["count"]):
            diffs.append(
                f"level {key}: {ra['count']} vs {rb['count']} positions"
            )
            continue
        if level_is_blocked(ra) != level_is_blocked(rb):
            needs_deep.append(
                f"level {key}: storage differs (v1 vs blocked v2); "
                "digests are not comparable"
            )
            continue
        for kind in ("keys", "cells"):
            if ra[f"{kind}_sha256"] != rb[f"{kind}_sha256"]:
                needs_deep.append(
                    f"level {key}: {kind} digests differ (content OR "
                    "encoding — deep compare decides)"
                )
        if level_is_blocked(ra) and ra.get("first_keys") != \
                rb.get("first_keys"):
            needs_deep.append(f"level {key}: block routing differs")
    if diffs:
        return "different", diffs + needs_deep
    if needs_deep:
        return "unknown", needs_deep
    return "same", []


def verify_for_serving(directory, verbose=None) -> bool:
    """Warm-start gate: the full :func:`check_db` pass a serving worker
    runs before it joins the ready set (ROADMAP: "warm replica start
    verified by check_db").

    Returns True when the DB was checked clean, False when verification
    is switched off (``GAMESMAN_SERVE_VERIFY=0`` — read-heavy restarts
    on trusted storage, where re-hashing a multi-GB DB per worker spawn
    is the wrong trade). Raises :class:`DbFormatError` on any problem:
    a worker must never start answering from a DB it cannot prove
    intact — the supervisor treats the failed spawn like any other
    worker death (backoff, storm breaker), so one rotted replica
    degrades to a restart loop instead of serving corrupt values.
    """
    if not env_bool("GAMESMAN_SERVE_VERIFY", True):
        return False
    problems = check_db(directory, verbose=verbose)
    if problems:
        raise DbFormatError(
            f"{directory}: serving verification failed: {problems[0]}"
            + (f" (+{len(problems) - 1} more)" if len(problems) > 1 else "")
        )
    return True
