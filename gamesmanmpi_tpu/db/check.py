"""Database integrity validation (CI-runnable, see tools/check_db.py).

Structural checks only — no game construction, no kernels, no backend
initialization (the package root's `import jax` runs, but nothing here
touches a device) — so the checker runs in seconds even where backend
bring-up is expensive or wedged, and a corrupted DB is caught before a
serving process ever mmaps it:

* manifest parses, format/version/fields are right (db/format.read_manifest)
* every level's shard files exist and match their sha256 checksums
* keys are strictly ascending (sorted + unique, the probe's contract),
  hold no padding sentinel, and match the manifest dtype and count
* cells are uint32, parallel to the keys, and every cell decodes to a
  DECIDED value (an UNDECIDED cell in a solved DB is a solver bug —
  lookups would report found-but-valueless)
"""

from __future__ import annotations

import pathlib

import numpy as np

from gamesmanmpi_tpu.core.bitops import sentinel_for
from gamesmanmpi_tpu.core.codec import unpack_cells_np
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.db.format import (
    DbFormatError,
    file_sha256,
    read_manifest,
)


def check_db(directory, verbose=None) -> list[str]:
    """Validate one DB directory; returns a list of problems (empty = OK).

    verbose: optional callable taking one progress line per level.
    """
    directory = pathlib.Path(directory)
    problems: list[str] = []
    try:
        manifest = read_manifest(directory)
    except DbFormatError as e:
        return [str(e)]
    try:
        dt = np.dtype(manifest["state_dtype"])
        sentinel = sentinel_for(dt)
    except TypeError as e:
        return [f"manifest state_dtype: {e}"]
    total = 0
    for key in sorted(manifest["levels"], key=int):
        rec = manifest["levels"][key]
        tag = f"level {key}"
        ok = True
        for kind in ("keys", "cells"):
            path = directory / rec[kind]
            if not path.exists():
                problems.append(f"{tag}: missing file {rec[kind]}")
                ok = False
                continue
            digest = file_sha256(path)
            if digest != rec[f"{kind}_sha256"]:
                problems.append(
                    f"{tag}: {kind} checksum mismatch "
                    f"({digest[:12]}… != {rec[f'{kind}_sha256'][:12]}…)"
                )
                ok = False
        if not ok:
            continue
        keys = np.load(directory / rec["keys"], mmap_mode="r")
        cells = np.load(directory / rec["cells"], mmap_mode="r")
        if keys.dtype != dt:
            problems.append(
                f"{tag}: keys dtype {keys.dtype}, manifest says {dt}"
            )
            continue
        if keys.shape[0] != rec["count"]:
            problems.append(
                f"{tag}: {keys.shape[0]} keys, manifest says {rec['count']}"
            )
        if cells.dtype != np.uint32 or cells.shape != keys.shape:
            problems.append(
                f"{tag}: cells are {cells.dtype}{list(cells.shape)}, "
                f"expected uint32[{keys.shape[0]}]"
            )
            continue
        if keys.shape[0]:
            if not np.all(keys[1:] > keys[:-1]):
                problems.append(f"{tag}: keys not strictly ascending")
            if keys[-1] == sentinel:
                problems.append(f"{tag}: keys contain the padding sentinel")
        # Decode through the one codec (not a private mask copy): a cell
        # layout change must not silently let the gate validate old bits.
        cell_values, _ = unpack_cells_np(np.asarray(cells))
        undecided = int(np.count_nonzero(cell_values == UNDECIDED))
        if undecided:
            problems.append(f"{tag}: {undecided} UNDECIDED cells")
        total += int(keys.shape[0])
        if verbose is not None:
            verbose(f"{tag}: {keys.shape[0]} positions OK")
    declared = manifest.get("num_positions")
    if declared is not None and declared != total:
        problems.append(
            f"manifest num_positions {declared} != shard total {total}"
        )
    return problems
