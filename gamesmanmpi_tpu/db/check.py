"""Database integrity validation (CI-runnable, see tools/check_db.py).

Structural checks only — no game construction, no kernels, no backend
initialization (the package root's `import jax` runs, but nothing here
touches a device) — so the checker runs in seconds even where backend
bring-up is expensive or wedged, and a corrupted DB is caught before a
serving process ever mmaps it:

* manifest parses, format/version/fields are right (db/format.read_manifest)
* every level's shard files exist and match their sha256 checksums
* keys are strictly ascending (sorted + unique, the probe's contract),
  hold no padding sentinel, and match the manifest dtype and count
* cells are uint32, parallel to the keys, and every cell decodes to a
  DECIDED value (an UNDECIDED cell in a solved DB is a solver bug —
  lookups would report found-but-valueless)
"""

from __future__ import annotations

import pathlib

import numpy as np

from gamesmanmpi_tpu.utils.env import env_bool

from gamesmanmpi_tpu.core.bitops import sentinel_for
from gamesmanmpi_tpu.core.codec import unpack_cells_np
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.db.format import (
    DbFormatError,
    file_sha256,
    read_manifest,
)


def check_db(directory, verbose=None) -> list[str]:
    """Validate one DB directory; returns a list of problems (empty = OK).

    verbose: optional callable taking one progress line per level.
    """
    directory = pathlib.Path(directory)
    problems: list[str] = []
    try:
        manifest = read_manifest(directory)
    except DbFormatError as e:
        return [str(e)]
    try:
        dt = np.dtype(manifest["state_dtype"])
        sentinel = sentinel_for(dt)
    except TypeError as e:
        return [f"manifest state_dtype: {e}"]
    total = 0
    for key in sorted(manifest["levels"], key=int):
        rec = manifest["levels"][key]
        tag = f"level {key}"
        ok = True
        for kind in ("keys", "cells"):
            path = directory / rec[kind]
            if not path.exists():
                problems.append(f"{tag}: missing file {rec[kind]}")
                ok = False
                continue
            digest = file_sha256(path)
            if digest != rec[f"{kind}_sha256"]:
                problems.append(
                    f"{tag}: {kind} checksum mismatch "
                    f"({digest[:12]}… != {rec[f'{kind}_sha256'][:12]}…)"
                )
                ok = False
        if not ok:
            continue
        keys = np.load(directory / rec["keys"], mmap_mode="r")
        cells = np.load(directory / rec["cells"], mmap_mode="r")
        if keys.dtype != dt:
            problems.append(
                f"{tag}: keys dtype {keys.dtype}, manifest says {dt}"
            )
            continue
        if keys.shape[0] != rec["count"]:
            problems.append(
                f"{tag}: {keys.shape[0]} keys, manifest says {rec['count']}"
            )
        if cells.dtype != np.uint32 or cells.shape != keys.shape:
            problems.append(
                f"{tag}: cells are {cells.dtype}{list(cells.shape)}, "
                f"expected uint32[{keys.shape[0]}]"
            )
            continue
        if keys.shape[0]:
            if not np.all(keys[1:] > keys[:-1]):
                problems.append(f"{tag}: keys not strictly ascending")
            if keys[-1] == sentinel:
                problems.append(f"{tag}: keys contain the padding sentinel")
        # Decode through the one codec (not a private mask copy): a cell
        # layout change must not silently let the gate validate old bits.
        cell_values, _ = unpack_cells_np(np.asarray(cells))
        undecided = int(np.count_nonzero(cell_values == UNDECIDED))
        if undecided:
            problems.append(f"{tag}: {undecided} UNDECIDED cells")
        total += int(keys.shape[0])
        if verbose is not None:
            verbose(f"{tag}: {keys.shape[0]} positions OK")
    declared = manifest.get("num_positions")
    if declared is not None and declared != total:
        problems.append(
            f"manifest num_positions {declared} != shard total {total}"
        )
    return problems


def verify_for_serving(directory, verbose=None) -> bool:
    """Warm-start gate: the full :func:`check_db` pass a serving worker
    runs before it joins the ready set (ROADMAP: "warm replica start
    verified by check_db").

    Returns True when the DB was checked clean, False when verification
    is switched off (``GAMESMAN_SERVE_VERIFY=0`` — read-heavy restarts
    on trusted storage, where re-hashing a multi-GB DB per worker spawn
    is the wrong trade). Raises :class:`DbFormatError` on any problem:
    a worker must never start answering from a DB it cannot prove
    intact — the supervisor treats the failed spawn like any other
    worker death (backoff, storm breaker), so one rotted replica
    degrades to a restart loop instead of serving corrupt values.
    """
    if not env_bool("GAMESMAN_SERVE_VERIFY", True):
        return False
    problems = check_db(directory, verbose=verbose)
    if problems:
        raise DbFormatError(
            f"{directory}: serving verification failed: {problems[0]}"
            + (f" (+{len(problems) - 1} more)" if len(problems) > 1 else "")
        )
    return True
