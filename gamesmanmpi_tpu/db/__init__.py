"""db: the persistent, immutable solved-position database.

The missing half of "a solve is only useful as a queryable database"
(PAPERS.md: Pentago's served lookup DB): per-level shards of (sorted
canonical keys, packed value+remoteness cells via core/codec), a JSON
manifest with per-shard checksums, a strict writer fed from a live solve
(engine level_sink hook) or an existing checkpoint directory, and a
mmap-backed reader whose batched lookup canonicalizes through the game's
symmetry before probing. Served over HTTP by gamesmanmpi_tpu.serve.

Reader/writer are loaded lazily (PEP 562): they pull in JAX (the reader
builds canonicalize kernels; the writer packs cells), while the
format helpers and the integrity checker deliberately do not — so
`tools/check_db.py` validates a DB in seconds without paying backend
bring-up, even where that is expensive (see check.py's docstring).
"""

from gamesmanmpi_tpu.db.check import check_db
from gamesmanmpi_tpu.db.format import (
    DbFormatError,
    parse_position,
    probe_sorted_np,
)

_LAZY = {
    "DbReader": "gamesmanmpi_tpu.db.reader",
    "DbWriter": "gamesmanmpi_tpu.db.writer",
    "export_checkpoint": "gamesmanmpi_tpu.db.writer",
    "export_result": "gamesmanmpi_tpu.db.writer",
}

__all__ = [
    "DbFormatError",
    "DbReader",
    "DbWriter",
    "check_db",
    "export_checkpoint",
    "export_result",
    "parse_position",
    "probe_sorted_np",
]


def __getattr__(name):
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)
