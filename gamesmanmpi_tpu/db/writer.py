"""DbWriter: build an immutable solved-position database.

Two feeds, one format (db/format.py):

* **Live solve** — `Solver(game, level_sink=writer.add_level_table,
  store_tables=False)` streams each resolved level into the writer the
  moment the backward pass finishes it, so an export never holds more
  than one level in host memory (the big-run contract).
* **Existing checkpoint** — `export_checkpoint` converts a
  `--checkpoint-dir` produced by any BFS engine (global per-level files
  or per-(level, shard) sets; `load_level` assembles + sorts the shards)
  so past solves become servable without re-solving.

The writer is strict where the reader is fast: keys must be strictly
ascending (sorted AND unique — the probe's contract), must not contain
the padding sentinel, and remoteness must fit the 30-bit cell field
un-clipped (a clipped remoteness would round-trip as the wrong answer;
better to refuse the export).
"""

from __future__ import annotations

import os
import pathlib

import numpy as np

from gamesmanmpi_tpu.compress import (
    CELL_CANDIDATES,
    DEFAULT_BLOCK_POSITIONS,
    KEY_CANDIDATES,
    encode_array,
)
from gamesmanmpi_tpu.core.bitops import sentinel_for
from gamesmanmpi_tpu.core.codec import pack_cells_np
from gamesmanmpi_tpu.core.values import MAX_REMOTENESS
from gamesmanmpi_tpu.db.format import (
    FORMAT_NAME,
    FORMAT_VERSION,
    FORMAT_VERSION_BLOCKS,
    DbFormatError,
    level_cell_blocks_name,
    level_cell_name,
    level_key_blocks_name,
    level_key_name,
    save_blocks_hashed,
    save_npy_hashed,
    write_manifest,
)
from gamesmanmpi_tpu.store import WriteTicket, default_store
from gamesmanmpi_tpu.utils.env import env_int

#: Export pipeline depth: at most this many levels' arrays parked
#: behind the write-behind worker before add_level blocks on the
#: oldest. Bounds export memory at O(depth) levels — the whole point of
#: the streaming level_sink — while the encode+DEFLATE+hash of level k
#: overlaps the solver resolving level k-1.
_EXPORT_PIPELINE = 2


class DbWriter:
    """Writes per-level shards, then seals the DB with a manifest.

    The manifest lands last (atomically): a crash mid-export leaves a
    directory the reader refuses, never a torn database.
    """

    def __init__(self, directory, game, spec: str, *,
                 overwrite: bool = False, compress: bool = False,
                 block_positions: int | None = None, store=None):
        """compress=True writes format v2: each level's keys/cells as
        independently-decodable blocks (compress/) with the per-block
        index in the manifest. block_positions overrides the block
        size (positions per block; default GAMESMAN_DB_BLOCK).

        Payload writes ride the block store's write-behind worker
        (ISSUE 11): ``add_level`` validates on the calling thread, then
        enqueues the encode+write+hash and returns — the solver's
        backward loop (level_sink feeds add_level synchronously) no
        longer waits on export DEFLATE. The manifest (the seal) is
        written at finalize AFTER every ticket resolves, preserving the
        write-then-seal discipline bit for bit."""
        self.compress = bool(compress)
        self.block_positions = int(
            block_positions
            if block_positions is not None
            else env_int("GAMESMAN_DB_BLOCK", DEFAULT_BLOCK_POSITIONS)
        )
        if self.compress and self.block_positions <= 0:
            raise DbFormatError(
                f"block size must be positive, got {self.block_positions}"
            )
        self.final_dir = pathlib.Path(directory)
        self.dir = self.final_dir
        if (self.final_dir / "manifest.json").exists():
            if not overwrite:
                raise DbFormatError(
                    f"{self.final_dir} already holds a finalized database "
                    "(pass overwrite=True to replace it)"
                )
            # Re-exports STAGE into a sibling directory and swap at
            # finalize: the export behind --overwrite may be an hours-long
            # solve, and a crash mid-way must leave the old database
            # serving, not a destroyed directory. The swap (rmtree + rename
            # at finalize) is the only window where neither DB exists, and
            # it is milliseconds, not the solve. The staging name is FIXED
            # (no pid): a rerun after a crash reclaims the leftover
            # instead of stranding one multi-GB orphan per attempt —
            # concurrent exports into one --out were never coherent anyway
            # (they would race the swap itself).
            import shutil

            self.dir = self.final_dir.with_name(
                f"{self.final_dir.name}.staging"
            )
            if self.dir.exists():
                shutil.rmtree(self.dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.game = game
        self.spec = spec
        self.store = store if store is not None else default_store()
        self._levels: dict = {}  # level -> record dict | WriteTicket
        self._finalized = False

    def level_record(self, level: int) -> dict:
        """The manifest record of one written level, waiting on its
        write-behind ticket if still in flight (export progress logging
        reads per-level stored bytes through this)."""
        rec = self._levels[level]
        if isinstance(rec, WriteTicket):
            rec = self._levels[level] = rec.result()
        return rec

    def _enqueue_level(self, level: int, job, path_name: str) -> None:
        """Park one level's encode+write+hash behind the store's worker
        and bound the pipeline: beyond _EXPORT_PIPELINE unresolved
        levels, block on the oldest — export memory stays O(depth)
        levels, exactly what the streaming level_sink contract
        promises. ``path_name`` is the level's REAL on-disk keys file
        (v1 .npy or v2 .gmb) — the store.writebehind torn-fault target
        must name a file the job actually writes."""
        self._levels[level] = self.store.write(
            job, path=str(self.dir / path_name)
        )
        # Insertion order == enqueue order == the worker's FIFO order.
        pending = [k for k in self._levels
                   if isinstance(self._levels[k], WriteTicket)]
        for k in pending[:-_EXPORT_PIPELINE]:
            self.level_record(k)

    def add_level(self, level: int, states, values=None, remoteness=None,
                  *, cells=None) -> None:
        """Write one level's (sorted states, packed cells) shard pair.

        Pass values+remoteness (packed here via pack_cells_np) or
        pre-packed cells. Validates the probe invariants at write time —
        a served wrong answer is far costlier than a failed export.
        """
        if self._finalized:
            raise DbFormatError("database already finalized")
        level = int(level)
        if level in self._levels:
            raise DbFormatError(f"level {level} written twice")
        states = np.asarray(states)
        dt = np.dtype(self.game.state_dtype)
        if states.dtype != dt:
            raise DbFormatError(
                f"level {level}: keys dtype {states.dtype} != game state "
                f"dtype {dt}"
            )
        if states.ndim != 1:
            raise DbFormatError(f"level {level}: keys must be 1-D")
        if states.shape[0] and states[-1] == sentinel_for(dt):
            raise DbFormatError(
                f"level {level}: keys contain the padding sentinel — "
                "pass only real states"
            )
        if not np.all(states[1:] > states[:-1]):
            raise DbFormatError(
                f"level {level}: keys must be strictly ascending "
                "(sorted and unique)"
            )
        if cells is None:
            remoteness = np.asarray(remoteness)
            if remoteness.size and (
                int(remoteness.min()) < 0
                or int(remoteness.max()) > MAX_REMOTENESS
            ):
                raise DbFormatError(
                    f"level {level}: remoteness outside [0, "
                    f"{MAX_REMOTENESS}] would not survive the cell packing"
                )
            cells = pack_cells_np(np.asarray(values), remoteness)
        cells = np.asarray(cells, dtype=np.uint32)
        if cells.shape != states.shape:
            raise DbFormatError(
                f"level {level}: {cells.shape[0]} cells for "
                f"{states.shape[0]} keys"
            )
        if self.compress:
            self._enqueue_level(
                level, self._blocked_level_job(level, states, cells),
                level_key_blocks_name(level),
            )
            return
        keys_name = level_key_name(level)
        cells_name = level_cell_name(level)

        def job(level=level, states=states, cells=cells):
            # One-pass save+hash: a post-save re-read would double
            # export I/O per level (save_npy_hashed streams the hash).
            return {
                "count": int(states.shape[0]),
                "keys": keys_name,
                "cells": cells_name,
                "keys_sha256": save_npy_hashed(
                    self.dir / keys_name, states
                ),
                "cells_sha256": save_npy_hashed(
                    self.dir / cells_name, cells
                ),
            }

        self._enqueue_level(level, job, keys_name)

    def _blocked_level_job(self, level: int, states, cells):
        """Format v2 level write job (runs on the write-behind worker —
        block encoding is the expensive half of a compressed export, so
        it overlaps the solver, not just the fsync): framed key/cell
        block streams + the per-block index (and per-block first keys,
        the probe's block router) destined for the manifest. Keys and
        cells share one blocking so block b of cells scores block b of
        keys."""
        bp = self.block_positions

        def job(level=level, states=states, cells=cells, bp=bp):
            keys_index, key_blobs = encode_array(states, bp,
                                                 KEY_CANDIDATES)
            cells_index, cell_blobs = encode_array(cells, bp,
                                                   CELL_CANDIDATES)
            keys_name = level_key_blocks_name(level)
            cells_name = level_cell_blocks_name(level)
            # One-pass save+hash, same discipline as the v1 path.
            keys_sha = save_blocks_hashed(self.dir / keys_name, key_blobs)
            cells_sha = save_blocks_hashed(self.dir / cells_name,
                                           cell_blobs)
            return {
                "count": int(states.shape[0]),
                "keys": keys_name,
                "cells": cells_name,
                "keys_sha256": keys_sha,
                "cells_sha256": cells_sha,
                "keys_blocks": keys_index,
                "cells_blocks": cells_index,
                # Per-block first key: the reader's block router (one
                # searchsorted over this small resident array finds the
                # only block a canonical key can live in). JSON holds
                # full uint64 range exactly — Python ints are arbitrary
                # precision.
                "first_keys": [
                    int(states[b]) for b in range(0, states.shape[0], bp)
                ],
                "raw_bytes": int(states.nbytes + cells.nbytes),
                "stored_bytes": int(
                    sum(keys_index["lengths"])
                    + sum(cells_index["lengths"])
                ),
            }

        return job

    def add_level_table(self, level: int, table) -> None:
        """Engine hook adapter: consumes a solve/engine.LevelTable."""
        self.add_level(level, table.states, table.values, table.remoteness)

    def abort(self) -> None:
        """Discard an unfinalized export: removes the staging directory
        (overwrite path) so a failed re-export leaves no orphan; a
        fresh-directory export keeps its partial files (unreadable — no
        manifest — and possibly useful for debugging)."""
        if self._finalized or self.dir == self.final_dir:
            return
        try:
            # Never rmtree under an in-flight payload write.
            self.store.drain()
        except Exception:  # noqa: BLE001 - aborting anyway
            pass
        import shutil

        shutil.rmtree(self.dir, ignore_errors=True)

    def finalize(self, extra: dict | None = None) -> dict:
        """Seal the DB: write the manifest (atomically, last). -> manifest.

        Every write-behind ticket resolves FIRST (payload on disk,
        hashes known), then the manifest lands — the same
        payload-before-seal order the synchronous writer had."""
        if not self._levels:
            raise DbFormatError("no levels written — refusing an empty DB")
        for level in list(self._levels):
            self.level_record(level)
        manifest = {
            "format": FORMAT_NAME,
            "version": (
                FORMAT_VERSION_BLOCKS if self.compress else FORMAT_VERSION
            ),
            "game": self.game.name,
            "spec": self.spec,
            "state_dtype": np.dtype(self.game.state_dtype).name,
            "sym": bool(getattr(self.game, "sym", False)),
            "num_positions": sum(
                rec["count"] for rec in self._levels.values()
            ),
            "levels": {
                str(k): self._levels[k] for k in sorted(self._levels)
            },
        }
        # Compiled gamedsl games carry their rules' identity: the canonical
        # spec document makes the DB self-describing (the reader rebuilds
        # the game even if the original .json moved), and the sha256 makes
        # `check_db --same-as` fail loudly across a rules change.
        if getattr(self.game, "spec_hash", None) is not None:
            manifest["spec_sha256"] = self.game.spec_hash
            manifest["game_spec"] = self.game.spec_doc
        if self.compress:
            manifest["compression"] = {
                "block_positions": self.block_positions,
                "raw_bytes": sum(
                    rec["raw_bytes"] for rec in self._levels.values()
                ),
                "stored_bytes": sum(
                    rec["stored_bytes"] for rec in self._levels.values()
                ),
            }
        if extra:
            manifest.update(extra)
        write_manifest(self.dir, manifest)
        if self.dir != self.final_dir:
            # Overwrite swap: the staged DB is complete (manifest and all),
            # so replace the old directory wholesale.
            import shutil

            shutil.rmtree(self.final_dir)
            os.rename(self.dir, self.final_dir)
            self.dir = self.final_dir
        self._finalized = True
        return manifest


def export_result(result, directory, spec: str, *,
                  overwrite: bool = False, compress: bool = False) -> dict:
    """One-shot export of an in-memory SolveResult's tables. -> manifest.

    For memory-bounded exports of big solves, prefer the streaming hook:
    Solver(game, level_sink=DbWriter(...).add_level_table,
    store_tables=False) — see solve/engine.py.
    """
    writer = DbWriter(directory, result.game, spec, overwrite=overwrite,
                      compress=compress)
    try:
        for level in sorted(result.levels):
            writer.add_level_table(level, result.levels[level])
        return writer.finalize()
    except BaseException:  # incl. KeyboardInterrupt: drop the staging dir
        writer.abort()
        raise


def export_checkpoint(checkpointer, game, spec: str, directory, *,
                      overwrite: bool = False, logger=None,
                      compress: bool = False) -> dict:
    """Convert an existing --checkpoint-dir into a servable DB. -> manifest.

    Consumes classic-engine checkpoints (global level files or sharded
    sets — `load_level` assembles and sorts shards, so multi-host big-run
    checkpoints convert without the solve ever assembling them). Dense
    checkpoints are refused: their flat per-index cell arrays cover the
    encodable superset, including fabricated classes the engine itself
    refuses to answer for.
    """
    manifest = checkpointer.load_manifest()
    if manifest.get("dense_levels"):
        raise DbFormatError(
            "dense checkpoint directories hold encodable-superset cells by "
            "perfect index, not reachable sorted states — serve those via "
            "the solver's --query path, or re-solve with the classic engine"
        )
    bound = manifest.get("game")
    if bound is not None and bound != game.name:
        raise DbFormatError(
            f"checkpoint directory belongs to game {bound!r}, not "
            f"{game.name!r}"
        )
    levels = checkpointer.completed_levels()
    if not levels:
        raise DbFormatError(
            f"{checkpointer.dir}: no completed levels to convert"
        )
    if levels != list(range(min(levels), max(levels) + 1)):
        import sys

        print(
            f"warning: checkpoint levels {levels} are not contiguous — "
            "the DB will answer 'not found' for the gaps",
            file=sys.stderr,
        )
    writer = DbWriter(directory, game, spec, overwrite=overwrite,
                      compress=compress)
    try:
        counts = {}
        for level in levels:
            table = checkpointer.load_level(level)
            writer.add_level_table(level, table)
            counts[level] = int(table.states.shape[0])
        manifest_out = writer.finalize()
        if logger is not None:
            # Log AFTER finalize: every ticket has resolved by then, so
            # the per-level compression figures (the material
            # tools/obs_report.py folds into its ratio line) cost no
            # ticket wait — logging per level DURING the loop would
            # block on each just-enqueued write and collapse the
            # export write-behind pipeline to depth 0.
            for level in levels:
                record = {
                    "phase": "export_db",
                    "level": level,
                    "n": counts[level],
                }
                rec = writer.level_record(level)
                if "stored_bytes" in rec:
                    record["raw_bytes"] = rec["raw_bytes"]
                    record["stored_bytes"] = rec["stored_bytes"]
                logger.log(record)
        return manifest_out
    except BaseException:  # incl. KeyboardInterrupt: drop the staging dir
        writer.abort()
        raise
