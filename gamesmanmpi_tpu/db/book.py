"""Resident opening book: the head of the query distribution, in RAM.

Query traffic over a solved game is overwhelmingly head-heavy — the 7x6
Connect-Four service (PAPERS.md) answers most real queries within the
first few plies. This module precomputes (value, remoteness, best move)
for every RAW position reachable within ``GAMESMAN_BOOK_PLIES`` moves of
the initial position and seals the table as ``book.gmb`` next to the
levels, recorded in the manifest like any other payload (file + sha256).
The server answers a book hit entirely from resident arrays: no
batcher, no canonicalize, no block decode — see serve/server.py's
``book`` span and ``gamesman_book_hits_total``.

RAW positions on purpose: clients hold raw states (they play raw moves
from the raw initial position — ``lookup_best``'s best children are raw
by contract), so storing the BFS set's raw spellings lets a book hit
skip the canonicalize kernel entirely. Value/remoteness/best are scored
through ``DbReader.lookup_best``, so the book is definitionally
consistent with the slow path it shadows; ``verify_book`` re-proves
that entry-by-entry (tools/check_db.py wires it into the serving gate).

The book rides the same invalidation story as every other fast path:
building it rewrites the manifest (atomically), which changes the DB
epoch; a rolling reload swaps reader + book together, and the ETag the
server derives from the epoch flips with it.
"""

from __future__ import annotations

import hashlib
import json
import pathlib
import struct

import numpy as np

from gamesmanmpi_tpu.core.codec import pack_cells_np, unpack_cells_np
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.db.format import (
    DbFormatError,
    file_sha256,
    read_manifest,
    write_manifest,
)

__all__ = ["BOOK_NAME", "OpeningBook", "build_book", "verify_book"]

BOOK_NAME = "book.gmb"
_MAGIC = b"GMBK1\x00\x00\x00"
_BFS_BUCKET = 256  # matches the reader's query-kernel bucket floor


def _children_of(reader, batch: np.ndarray) -> np.ndarray:
    """Unique raw children of a raw-position batch (terminal positions
    expand to nothing), via the reader's cached dbexpand kernel."""
    from gamesmanmpi_tpu.db.reader import _expand_builder
    from gamesmanmpi_tpu.ops.padding import bucket_size, pad_to

    cap = bucket_size(batch.shape[0], _BFS_BUCKET)
    raw, _canon, mask, _clv = reader._cpu_kernel(
        "dbexpand", cap, _expand_builder, pad_to(batch, cap)
    )
    k = batch.shape[0]
    raw = np.asarray(raw)[:k]
    mask = np.asarray(mask)[:k]
    kids = np.unique(raw[mask])
    return kids[kids != reader.game.sentinel]


def _bfs_positions(reader, plies: int) -> np.ndarray:
    """Sorted unique raw positions within `plies` moves of the initial
    position (the initial position itself is ply 0)."""
    dtype = np.dtype(reader.game.state_dtype)
    seen = np.asarray([int(reader.game.initial_state())], dtype=dtype)
    frontier = seen
    for _ in range(int(plies)):
        if frontier.size == 0:
            break
        kids = _children_of(reader, frontier)
        frontier = np.setdiff1d(kids, seen, assume_unique=False)
        seen = np.union1d(seen, frontier)
    return seen


# Payload streams to its final name; the caller records the returned
# sha256 in the manifest, which write_manifest replaces atomically — the
# same write-then-seal contract as format.save_npy_hashed.
# sealed-write: GM801 write-then-seal payload helper (see above)
def _write_book_file(path, header: dict, positions, cells, best) -> str:
    blob = json.dumps(header, sort_keys=True).encode()
    h = hashlib.sha256()
    with open(path, "wb") as fh:
        for chunk in (
            _MAGIC,
            struct.pack("<I", len(blob)),
            blob,
            np.ascontiguousarray(positions).astype(
                positions.dtype.newbyteorder("<"), copy=False).tobytes(),
            np.ascontiguousarray(cells).astype("<u4", copy=False).tobytes(),
            np.ascontiguousarray(best).astype(
                best.dtype.newbyteorder("<"), copy=False).tobytes(),
        ):
            h.update(chunk)
            fh.write(chunk)
    return h.hexdigest()


def build_book(directory, plies: int, *, game=None) -> dict:
    """Build + seal the opening book of a finalized DB -> the manifest
    ``book`` record. Runs AFTER finalize (it opens a reader over the
    directory), rewrites the manifest atomically, and therefore bumps
    the DB epoch — callers do this before serving, never under it.
    """
    from gamesmanmpi_tpu.db.reader import DbReader

    plies = int(plies)
    if plies < 0:
        raise ValueError(f"book plies must be >= 0, got {plies}")
    directory = pathlib.Path(directory)
    reader = DbReader(directory, game)
    try:
        positions = _bfs_positions(reader, plies)
        values, rem, found, best = reader.lookup_best(positions)
        # A finalized strong solve answers every reachable position;
        # drop (don't invent) anything it does not — a book must never
        # hold an entry the slow path would refuse.
        positions = positions[found]
        best = best[found]
        cells = pack_cells_np(values[found], rem[found])
        header = {
            "game": reader.game.name,
            "plies": plies,
            "count": int(positions.size),
            "state_dtype": np.dtype(reader.game.state_dtype).name,
            "sentinel": int(reader.game.sentinel),
        }
        sha = _write_book_file(
            directory / BOOK_NAME, header, positions, cells, best
        )
        manifest = dict(reader.manifest)
        manifest["book"] = {
            "file": BOOK_NAME,
            "sha256": sha,
            "plies": plies,
            "count": int(positions.size),
        }
        write_manifest(directory, manifest)
        return manifest["book"]
    finally:
        reader.close()


class OpeningBook:
    """Resident, immutable (positions, cells, best) arrays + searchsorted
    lookup — the whole book lives in process memory once loaded."""

    __slots__ = ("positions", "cells", "best", "plies", "sentinel")

    def __init__(self, positions, cells, best, *, plies: int,
                 sentinel: int):
        self.positions = positions
        self.cells = cells
        self.best = best
        self.plies = int(plies)
        self.sentinel = sentinel

    @classmethod
    def load(cls, directory, manifest: dict | None = None, *,
             verify: bool = True):
        """Load a sealed book, or None when the manifest records none.
        ``verify`` re-hashes the file against the manifest seal (cheap:
        books are head-of-distribution small) — a mismatch raises
        DbFormatError so a worker warm start refuses the directory
        instead of serving a tampered fast path."""
        directory = pathlib.Path(directory)
        if manifest is None:
            manifest = read_manifest(directory)
        rec = manifest.get("book")
        if not rec:
            return None
        path = directory / rec["file"]
        if not path.exists():
            raise DbFormatError(
                f"{directory}: manifest records book {rec['file']!r} "
                "but the file is missing"
            )
        if verify and file_sha256(path) != rec["sha256"]:
            raise DbFormatError(
                f"{path}: sha256 mismatch vs manifest book seal"
            )
        # store-io: sealed opening-book payload read (sha-verified above)
        blob = path.read_bytes()
        if blob[: len(_MAGIC)] != _MAGIC:
            raise DbFormatError(f"{path}: not a GMBK1 opening book")
        (hlen,) = struct.unpack_from("<I", blob, len(_MAGIC))
        off = len(_MAGIC) + 4
        try:
            header = json.loads(blob[off: off + hlen])
        except ValueError as e:
            raise DbFormatError(f"{path}: bad book header: {e}") from e
        off += hlen
        count = int(header["count"])
        sdt = np.dtype(header["state_dtype"]).newbyteorder("<")
        positions = np.frombuffer(blob, dtype=sdt, count=count, offset=off)
        off += positions.nbytes
        cells = np.frombuffer(blob, dtype="<u4", count=count, offset=off)
        off += cells.nbytes
        best = np.frombuffer(blob, dtype=sdt, count=count, offset=off)
        if best.size != count:
            raise DbFormatError(f"{path}: truncated book payload")
        return cls(
            positions, cells, best,
            plies=int(header["plies"]),
            sentinel=np.dtype(sdt.newbyteorder("="))
            .type(header["sentinel"]),
        )

    def __len__(self) -> int:
        return int(self.positions.size)

    def lookup(self, states):
        """Batched resident probe: raw positions -> (values, remoteness,
        found, best) with the exact shapes/miss semantics of
        ``DbReader.lookup_best`` (UNDECIDED/0/sentinel on miss)."""
        q = np.asarray(states, dtype=self.positions.dtype)
        k = int(q.shape[0])
        if k == 0 or self.positions.size == 0:
            return (
                np.full(k, UNDECIDED, dtype=np.uint8),
                np.zeros(k, dtype=np.int32),
                np.zeros(k, dtype=bool),
                np.full(k, self.sentinel, dtype=self.positions.dtype),
            )
        idx = np.searchsorted(self.positions, q)
        np.clip(idx, 0, self.positions.size - 1, out=idx)
        found = self.positions[idx] == q
        values, rem = unpack_cells_np(self.cells[idx])
        values = np.where(found, values, UNDECIDED).astype(np.uint8)
        rem = np.where(found, rem, 0).astype(np.int32)
        best = np.where(found, self.best[idx], self.sentinel).astype(
            self.positions.dtype
        )
        return values, rem, found, best


def verify_book(directory, *, game=None, batch: int = 8192) -> list:
    """Re-probe EVERY book entry through the reader's slow path ->
    problem strings ([] = the book answers exactly what the DB does).
    The deep half of the serving gate: db/check.py checks the seal
    structurally; this proves the shadowed answers, so the hot path
    keeps check_db's "never a wrong answer" guarantee."""
    from gamesmanmpi_tpu.db.reader import DbReader

    directory = pathlib.Path(directory)
    manifest = read_manifest(directory)
    if not manifest.get("book"):
        return [f"{directory}: manifest records no book to verify"]
    problems: list = []
    book = OpeningBook.load(directory, manifest)
    reader = DbReader(directory, game)
    try:
        for lo in range(0, len(book), batch):
            pos = np.asarray(book.positions[lo: lo + batch])
            bv, br = unpack_cells_np(np.asarray(book.cells[lo: lo + batch]))
            bb = np.asarray(book.best[lo: lo + batch])
            rv, rr, rfound, rb = reader.lookup_best(pos)
            bad = (
                ~rfound | (bv != rv) | (br != rr) | (bb != rb)
            )
            for i in np.nonzero(bad)[0][:20]:
                problems.append(
                    f"book entry {hex(int(pos[i]))}: book says "
                    f"(v={int(bv[i])}, r={int(br[i])}, "
                    f"best={hex(int(bb[i]))}), reader says "
                    f"(v={int(rv[i])}, r={int(rr[i])}, "
                    f"best={hex(int(rb[i]))}, found={bool(rfound[i])})"
                )
            nbad = int(bad.sum())
            if nbad > 20:
                problems.append(
                    f"... +{nbad - 20} more mismatched book entries "
                    f"in batch at {lo}"
                )
    finally:
        reader.close()
    return problems
