"""Solved-position database: on-disk format + the shared probe primitive.

A strongly-solved game is only useful as a *queryable database* — the
Pentago solve culminates in a served lookup DB, and "Compressed Game
Solving" is entirely about shipping such tables (PAPERS.md). This module
defines the immutable directory format both halves of that story share:

    db_dir/
      manifest.json             format id, version, game identity, per-level
                                records with counts + sha256 checksums
      level_NNNN.keys.npy       sorted canonical states (game state dtype)
      level_NNNN.cells.npy      packed (value, remoteness) uint32 cells
                                (core/codec.py), parallel to the keys

Format **v2** (ISSUE 9, `export-db --compress`) replaces the per-level
.npy pair with block-compressed streams the reader decodes on probe:

      level_NNNN.keys.gmb       framed key blocks (compress/blocks):
                                fixed position-count blocks, each
                                independently decodable
      level_NNNN.cells.gmb      framed cell blocks, parallel blocking
                                — block b of cells scores block b of keys

with the per-block index (codec, length, crc32) and each block's first
key in the manifest level record, so a probe touches exactly the blocks
its queries land in. v1 stays readable forever; both versions share
this manifest, the same probe contract, and the same checker.

Design rules, in order of importance:

* **Immutable once finalized.** The manifest is written last (atomic
  os.replace, same discipline as utils/checkpoint.py): a directory without
  a manifest is an aborted export, never a half-readable DB.
* **Plain .npy per level, not .npz**: `np.load(mmap_mode="r")` memory-maps
  .npy directly, so a reader probes a multi-GB level by touching O(log n)
  pages — .npz would force a full decompress-to-RAM on open.
* **The cell layout IS the HBM table layout** (sorted keys + packed u32
  cells), so export from a live solve or a checkpoint is a copy, not a
  transform, and `pack_cells`/`unpack_cells` round-trip bit-exactly.

`probe_sorted_np` (re-exported from core/probe.py, where it lives so the
solver and checkpoint layers can share it without importing upward) is
the one host-side canonicalize→probe search all query paths use: the
NumPy twin of ops/lookup.py's sorted-level search — index by
searchsorted, clip, confirm by equality, sentinel never matches because
writers refuse to store it.
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib

import numpy as np

# Re-exported here because the probe is part of the DB format's API; it
# lives in core/ (numpy-only) so solve/ and utils/ can share it without
# importing upward into this package.
from gamesmanmpi_tpu.core.probe import probe_sorted_np  # noqa: F401

FORMAT_NAME = "gamesman-db"
FORMAT_VERSION = 1
#: Format v2 (ISSUE 9): per-level keys/cells stored as block-compressed
#: streams (compress/blocks framing) with the per-block index in this
#: manifest; v1 levels are plain mmap-able .npy. Readers speak both,
#: forever — v1 directories never need re-exporting.
FORMAT_VERSION_BLOCKS = 2
SUPPORTED_VERSIONS = (FORMAT_VERSION, FORMAT_VERSION_BLOCKS)

MANIFEST_NAME = "manifest.json"


class DbFormatError(ValueError):
    """The directory is not a valid solved-position database."""


def level_key_name(level: int) -> str:
    return f"level_{level:04d}.keys.npy"


def level_cell_name(level: int) -> str:
    return f"level_{level:04d}.cells.npy"


def level_key_blocks_name(level: int) -> str:
    """v2: the level's framed key-block stream (compress/blocks)."""
    return f"level_{level:04d}.keys.gmb"


def level_cell_blocks_name(level: int) -> str:
    """v2: the level's framed cell-block stream."""
    return f"level_{level:04d}.cells.gmb"


def level_is_blocked(rec: dict) -> bool:
    """True when a manifest level record is block-compressed (v2)."""
    return "keys_blocks" in rec


def file_sha256(path, chunk: int = 1 << 22) -> str:
    """Streaming sha256 of a file (levels can be larger than RAM)."""
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            block = fh.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def write_manifest(directory, manifest: dict) -> None:
    """Atomic manifest write: readers see a complete DB or none at all."""
    directory = pathlib.Path(directory)
    tmp = directory / f"{MANIFEST_NAME}.{os.getpid()}.tmp"
    tmp.write_text(json.dumps(manifest, indent=1, sort_keys=True))
    os.replace(tmp, directory / MANIFEST_NAME)


def read_manifest(directory) -> dict:
    """Load + structurally validate a DB manifest; raises DbFormatError."""
    path = pathlib.Path(directory) / MANIFEST_NAME
    if not path.exists():
        raise DbFormatError(
            f"{directory}: no {MANIFEST_NAME} — not a solved-position "
            "database (or an export that never finalized)"
        )
    try:
        manifest = json.loads(path.read_text())
    except json.JSONDecodeError as e:
        raise DbFormatError(
            f"{path}: manifest is not valid JSON ({e})"
        ) from e
    if manifest.get("format") != FORMAT_NAME:
        raise DbFormatError(
            f"{path}: format {manifest.get('format')!r}, "
            f"expected {FORMAT_NAME!r}"
        )
    if manifest.get("version") not in SUPPORTED_VERSIONS:
        raise DbFormatError(
            f"{path}: version {manifest.get('version')!r} not supported "
            f"(reader speaks {', '.join(map(str, SUPPORTED_VERSIONS))})"
        )
    for field in ("game", "spec", "state_dtype", "levels"):
        if field not in manifest:
            raise DbFormatError(f"{path}: missing manifest field {field!r}")
    return manifest


def parse_position(game, raw) -> int:
    """Parse one user-supplied position and range-check it.

    raw: an int, or a decimal / 0x-hex string (the CLI --query spelling).
    The shared front door of `cli query` and the HTTP server's
    POST /query, so both routes accept and refuse exactly the same
    inputs. Raises ValueError/TypeError with a per-position message.
    Non-integer JSON numbers (42.7) and booleans are refused, not
    truncated — int(42.7) would silently answer for a different position
    than the one queried.
    """
    if isinstance(raw, str):
        # Length-cap before int(): a 63-bit position needs <= 19 decimal
        # (or 2+16 hex) characters, while int() on a multi-MB digit
        # string is quadratic on this runtime — a client could pin a
        # handler thread with one absurd literal.
        if len(raw) > 32:
            raise ValueError("position literal too long")
        state = int(raw, 0)
    elif isinstance(raw, int) and not isinstance(raw, bool):
        state = raw
    else:
        raise TypeError(
            f"expected an integer or a numeric string, got "
            f"{type(raw).__name__}"
        )
    if not 0 <= state < (1 << game.state_bits):
        raise ValueError(
            f"outside the game's {game.state_bits}-bit state space"
        )
    return state


# Payload streams to its final name; the caller records the returned
# sha256 in the manifest, which write_manifest replaces atomically — a
# death mid-write leaves an unsealed stray, never a half-readable DB.
# sealed-write: GM801 write-then-seal payload helper (see above)
def save_npy_hashed(path, arr: np.ndarray) -> str:
    """np.save + sha256 of the written bytes in ONE pass.

    Hashing the stream as it is written (instead of re-reading the file
    afterward) halves export I/O per level — the writer runs
    synchronously inside the solver's backward loop via level_sink, and
    levels are multi-GB at the design target.
    """

    class _HashingWriter:
        # Duck-typed file object WITHOUT fileno(): np.save then routes
        # the array through buffered write() calls we can hash.
        def __init__(self, fh):
            self.fh = fh
            self.h = hashlib.sha256()

        def write(self, data):
            self.h.update(data)
            return self.fh.write(data)

    with open(path, "wb") as fh:
        writer = _HashingWriter(fh)
        np.save(writer, arr)
        return writer.h.hexdigest()


# sealed-write: same write-then-seal contract as save_npy_hashed.
def save_blocks_hashed(path, blobs) -> str:
    """Write a framed block stream (compress/blocks.encode_array output)
    + sha256 of the written bytes in ONE pass — the v2 twin of
    save_npy_hashed, same export-I/O discipline."""
    h = hashlib.sha256()
    with open(path, "wb") as fh:
        for blob in blobs:
            h.update(blob)
            fh.write(blob)
    return h.hexdigest()
