"""DbReader: mmap-backed vectorized lookup into a solved-position DB.

The read side of db/format.py: open the manifest, reconstruct the game
from its registry spec, memory-map each level's (keys, cells) pair
lazily, and answer batches of raw positions with (value, remoteness).
Queries are canonicalized through the game's symmetry before probing —
exactly the contract of SolveResult.lookup — so a sym=1 database answers
for every member of a stored class. The per-level search is the same
searchsorted-confirm shape as ops/lookup.py, in its NumPy form
(db/format.probe_sorted_np): on host, against a memory-mapped level, a
binary search touches O(log n) pages, which is what makes a multi-GB
level servable from disk without loading it.

Canonicalize + level_of run as one batched kernel on the host CPU
backend (same policy as solve/engine.canonical_scalar: a query batch
gains nothing from the accelerator, and on the relay every accelerator
compile costs ~15 s), padded to power-of-two buckets so the serving
process compiles O(log max-batch) programs, not one per batch size.
"""

from __future__ import annotations

import math
import pathlib
import time

import jax
import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.compress import BlockCorruptError
from gamesmanmpi_tpu.core.codec import unpack_cells_np
from gamesmanmpi_tpu.core.values import LOSE, TIE, UNDECIDED, WIN
from gamesmanmpi_tpu.db.format import (
    MANIFEST_NAME,
    DbFormatError,
    file_sha256,
    level_is_blocked,
    probe_sorted_np,
    read_manifest,
)
from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.obs.qtrace import qspan
from gamesmanmpi_tpu.ops.padding import bucket_size, pad_to
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.solve.engine import get_kernel, undecided_mask
from gamesmanmpi_tpu.store import (
    BlockStore,
    SealedBlockStream,
    TieredCache,
    default_store,
    open_npy_mmap,
)
from gamesmanmpi_tpu.utils.env import env_bool, env_int, env_opt

# Smallest query-kernel capacity: batches are tiny next to frontiers, and
# every distinct capacity is a compiled program.
_MIN_QUERY_BUCKET = 256


def _canon_builder(game):
    def f(states):
        c = game.canonicalize(states)
        return c, game.level_of(c)

    return f


def _expand_builder(game):
    # Expands the RAW queried positions and returns both the raw children
    # (the legal moves of the position the client actually holds — a
    # sym=1 best-move answer must be playable from it, not from its class
    # representative) and their canonical twins for probing; value and
    # remoteness are sym-invariant, so the canonical probe scores the raw
    # move exactly. Children of padding/terminal lanes become sentinel, so
    # a junk lane can never accidentally probe a real state. Child levels
    # come out of the same program — no second canonicalize/level pass
    # over the k*max_moves expansion set (the biggest serving kernel).
    def f(states):
        children, mask = game.expand(states)
        mask = mask & undecided_mask(game, states)[:, None]
        raw = jnp.where(mask, children, game.sentinel)
        canon = jnp.where(mask, game.canonicalize(children), game.sentinel)
        return raw, canon, mask, game.level_of(canon.reshape(-1))

    return f


class DbReader:
    """Read-only handle on a finalized solved-position database."""

    def __init__(self, directory, game=None, *, verify: bool = False,
                 registry=None, shm=None):
        self.dir = pathlib.Path(directory)
        self.manifest = read_manifest(self.dir)
        #: DB epoch — the manifest sha. THE invalidation token of every
        #: fast path layered over this reader (ISSUE 18): shared-memory
        #: block slots are stamped with it, the server's ETag embeds
        #: it, and the opening book implicitly carries it (building a
        #: book rewrites the manifest). A reload that changes the DB
        #: changes the epoch, and everything stale becomes a miss.
        self.epoch = file_sha256(self.dir / MANIFEST_NAME)
        reg = registry or default_registry()
        self._m_probe_secs = reg.histogram(
            "gamesman_db_probe_seconds",
            "wall seconds per batched level probe (searchsorted + "
            "cell gather)",
        )
        self._m_probe_queries = reg.counter(
            "gamesman_db_probe_queries_total", "positions probed"
        )
        self._m_page_touches = reg.counter(
            "gamesman_db_mmap_page_touches_total",
            "estimated mmap pages touched: ceil(log2(level keys)) per "
            "binary-search query plus one cells page per hit — the "
            "working-set denominator that says whether a level is being "
            "served from page cache or disk",
        )
        if game is None and self.manifest.get("game_spec") is not None:
            # gamedsl DB: the manifest embeds the canonical spec document,
            # so the game reconstructs even when the original .json file
            # moved or changed — the DB answers for the rules it was
            # solved under, never for whatever the path now holds.
            from gamesmanmpi_tpu.gamedsl import GameSpec, SpecError
            from gamesmanmpi_tpu.gamedsl.compiler import compile_spec

            try:
                game = compile_spec(
                    GameSpec.from_dict(self.manifest["game_spec"])
                )
            except SpecError as e:
                raise DbFormatError(
                    f"{self.dir}: embedded game_spec is not "
                    f"compilable: {e}"
                ) from e
        if game is None:
            from gamesmanmpi_tpu.games import get_game

            try:
                game = get_game(self.manifest["spec"])
            except (KeyError, ValueError) as e:
                raise DbFormatError(
                    f"{self.dir}: manifest spec "
                    f"{self.manifest['spec']!r} is not constructible: {e}"
                ) from e
        if game.name != self.manifest["game"]:
            raise DbFormatError(
                f"{self.dir} belongs to game {self.manifest['game']!r}, "
                f"not {game.name!r}"
            )
        if np.dtype(game.state_dtype).name != self.manifest["state_dtype"]:
            raise DbFormatError(
                f"{self.dir}: state dtype {self.manifest['state_dtype']} "
                f"!= game's {np.dtype(game.state_dtype).name}"
            )
        self.game = game
        self._levels = {
            int(k): rec for k, rec in self.manifest["levels"].items()
        }
        self._arrays: dict = {}
        self._blocked: dict = {}
        self._shm = shm  # cross-worker decoded-block tier (store/shm.py)
        self._store = None
        self._private_store = False
        self._m_decode_secs = None
        self._m_cache_hits = self._m_cache_misses = None
        self._hits = 0  # guarded-by: _stats_lock
        self._misses = 0  # guarded-by: _stats_lock
        self._stats_lock = None
        if any(level_is_blocked(rec) for rec in self._levels.values()):
            import threading

            # Decompress-on-probe state (format v2), ISSUE 11: decoded
            # blocks live in the SHARED block-store cache (one byte
            # budget across every reader/route in the process — the
            # private per-reader LRUs this replaces each held their own
            # copy of the hot head). GAMESMAN_DB_CACHE_MB, when set
            # explicitly, still carves a private store for this reader
            # (legacy per-reader budget; tests use it to force
            # eviction), labeled so two private caches on one registry
            # keep separable series.
            if env_opt("GAMESMAN_DB_CACHE_MB"):
                self._store = BlockStore(
                    cache=TieredCache(
                        max(1, env_int("GAMESMAN_DB_CACHE_MB", 64)) << 20,
                        registry=reg, labels={"db": self.dir.name},
                    ),
                    prefetch_threads=0, writebehind=False, registry=reg,
                    labels={"db": self.dir.name},
                )
                self._private_store = True
            else:
                self._store = default_store()
            self._stats_lock = threading.Lock()
            # Per-reader hit/miss series survive the unification: the
            # db label separates routes within one worker, the worker
            # label separates workers (docs/OBSERVABILITY.md).
            self._m_cache_hits = reg.counter(
                "gamesman_db_cache_hits_total",
                "probes answered from an already-decoded hot block",
                db=self.dir.name,
            )
            self._m_cache_misses = reg.counter(
                "gamesman_db_cache_misses_total",
                "probes that had to decode a cold block",
                db=self.dir.name,
            )
            self._m_decode_secs = reg.histogram(
                "gamesman_db_block_decode_seconds",
                "wall seconds decoding one cold (keys, cells) block pair "
                "on the probe path (cache misses only)",
                db=self.dir.name,
            )
        # The resident opening book (db/book.py) rides the reader so
        # every consumer — fleet worker, single-process server, CLI —
        # gets the short path for free when the manifest seals one.
        # Loading re-hashes the seal; a corrupt book refuses the reader
        # (never a wrong fast answer). GAMESMAN_SERVE_BOOK=0 opts out.
        self.book = None
        if self.manifest.get("book") and env_bool("GAMESMAN_SERVE_BOOK",
                                                  True):
            from gamesmanmpi_tpu.db.book import OpeningBook

            self.book = OpeningBook.load(self.dir, self.manifest)
        if verify:
            from gamesmanmpi_tpu.db.check import check_db

            problems = check_db(self.dir)
            if problems:
                raise DbFormatError(
                    f"{self.dir}: integrity check failed: {problems[0]}"
                    + (f" (+{len(problems) - 1} more)"
                       if len(problems) > 1 else "")
                )

    # ------------------------------------------------------------- plumbing

    @property
    def num_positions(self) -> int:
        return int(self.manifest.get(
            "num_positions",
            sum(rec["count"] for rec in self._levels.values()),
        ))

    @property
    def levels(self) -> list[int]:
        return sorted(self._levels)

    def _level_arrays(self, level: int):
        """(keys, cells) of one level, memory-mapped on first touch
        (store/sealed.open_npy_mmap — the v1 door)."""
        pair = self._arrays.get(level)
        if pair is None:
            rec = self._levels[level]
            keys = open_npy_mmap(self.dir / rec["keys"])
            cells = open_npy_mmap(self.dir / rec["cells"])
            pair = self._arrays[level] = (keys, cells)
        return pair

    def _blocked_level(self, level: int) -> SealedBlockStream:
        """The v2 probe handle of one level, opened on first touch.
        Lock-free under concurrent probes: a race opens two handles and
        the setdefault loser closes its fds — strictly cheaper than
        serializing every first touch behind a lock."""
        bl = self._blocked.get(level)
        if bl is None:
            try:
                fresh = SealedBlockStream(
                    self.dir, level, self._levels[level]
                )
            except (BlockCorruptError, OSError) as e:
                raise DbFormatError(
                    f"{self.dir}: level {level} block stream unreadable: "
                    f"{e}"
                ) from e
            bl = self._blocked.setdefault(level, fresh)
            if bl is not fresh:
                fresh.close()
        return bl

    def cache_stats(self):
        """Hot-block cache counters (dict), or None for a v1 DB — the
        serving batcher rides these on its serve_batch records so
        per-worker cache behavior lands in the JSONL stream. hits and
        misses are THIS reader's probes; bytes/blocks/evictions are the
        backing store cache's (shared across readers unless
        GAMESMAN_DB_CACHE_MB carved a private one)."""
        if self._store is None:
            return None
        backing = self._store.cache.stats()
        with self._stats_lock:
            hits, misses = self._hits, self._misses
        return {
            "hits": hits,
            "misses": misses,
            "evictions": backing["evictions"],
            "bytes": backing["bytes"],
            "blocks": backing["blocks"],
        }

    def close(self) -> None:
        """Drop the mmaps, close block-stream fds (everything also dies
        with the reader). Decoded blocks: a PRIVATE store's cache is
        cleared; the shared store keeps its entries — they are keyed by
        stream inode, so they can never leak into a different DB, and
        another reader of the same DB may still be serving them."""
        self._arrays.clear()
        for bl in self._blocked.values():
            bl.close()
        self._blocked.clear()
        if self._private_store and self._store is not None:
            self._store.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _cpu_kernel(self, kind: str, cap: int, builder, arg):
        """Run a cached batched kernel on the host CPU backend."""
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            cpu = None
        if cpu is not None:
            with jax.default_device(cpu):
                fn = get_kernel(self.game, f"{kind}_cpu", cap, builder)
                return fn(jnp.asarray(arg))
        fn = get_kernel(self.game, kind, cap, builder)
        return fn(jnp.asarray(arg))

    def _canon_levels(self, q: np.ndarray):
        """Batched canonicalize + level_of: [K] -> (canon [K], levels [K])."""
        cap = bucket_size(q.shape[0], _MIN_QUERY_BUCKET)
        with qspan("canonicalize", queries=int(q.shape[0])):
            c, lv = self._cpu_kernel(
                "dbcanon", cap, _canon_builder, pad_to(q, cap)
            )
        n = q.shape[0]
        return (
            np.asarray(c)[:n].astype(self.game.state_dtype),
            np.asarray(lv)[:n],
        )

    # -------------------------------------------------------------- queries

    def lookup(self, queries) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Batched probe: raw positions -> (values, remoteness, found).

        queries: array-like of packed positions (any symmetry-class
        member). Returns (values [K] uint8 — UNDECIDED on miss,
        remoteness [K] int32 — 0 on miss, found [K] bool). One
        searchsorted per distinct level present in the batch.
        """
        q = np.ascontiguousarray(
            np.asarray(queries, dtype=self.game.state_dtype)
        )
        if q.shape[0] == 0:
            return (
                np.zeros(0, dtype=np.uint8),
                np.zeros(0, dtype=np.int32),
                np.zeros(0, dtype=bool),
            )
        return self._probe(*self._canon_levels(q))

    def _probe(self, canon: np.ndarray, levels: np.ndarray):
        """Probe ALREADY-CANONICAL states with known levels (the second
        half of lookup; split out so lookup_best canonicalizes a batch
        once and reuses it for both the probe and the expansion)."""
        k = canon.shape[0]
        faults.fire("db.probe", queries=k)
        t0 = time.perf_counter()
        values = np.full(k, UNDECIDED, dtype=np.uint8)
        remoteness = np.zeros(k, dtype=np.int32)
        found = np.zeros(k, dtype=bool)
        real = canon != self.game.sentinel
        pages = 0
        for lv in np.unique(levels[real]):
            rec = self._levels.get(int(lv))
            if rec is None:
                continue
            sel = np.nonzero(real & (levels == lv))[0]
            if level_is_blocked(rec):
                self._probe_blocked_level(
                    int(lv), canon, sel, values, remoteness, found
                )
                continue
            keys, cells = self._level_arrays(int(lv))
            with qspan("searchsorted", level=int(lv),
                       queries=int(sel.size)):
                idx, hit = probe_sorted_np(keys, canon[sel])
            hsel = sel[hit]
            if hsel.size:
                v, r = unpack_cells_np(np.asarray(cells[idx[hit]]))
                values[hsel] = v
                remoteness[hsel] = r
                found[hsel] = True
            # Page-touch model, not a kernel counter: each binary search
            # descends ~log2(n) key pages (upper levels share pages and
            # stay cached, so this is a ceiling), each hit reads one
            # cells page.
            pages += sel.size * max(
                1, math.ceil(math.log2(max(int(keys.shape[0]), 2)))
            ) + int(hsel.size)
        self._m_probe_queries.inc(k)
        self._m_page_touches.inc(pages)
        self._m_probe_secs.observe(time.perf_counter() - t0)
        return values, remoteness, found

    def _probe_blocked_level(self, lv: int, canon, sel, values,
                             remoteness, found) -> None:
        """Decompress-on-probe for one v2 level: route each query to its
        block by first_keys, decode only the touched blocks (hot-block
        LRU first), then the same searchsorted-confirm as v1 inside the
        decoded block. Corruption discovered mid-probe (torn block, crc
        mismatch) raises DbFormatError so the serving breaker counts a
        reader fault instead of a wrong answer going out."""
        bl = self._blocked_level(lv)
        if bl.num_blocks == 0 or sel.size == 0:
            return
        q = canon[sel]
        # side="right" - 1: the block whose first key is <= q. Queries
        # below the level's first key clip to block 0, where the
        # equality confirm rejects them (same sentinel-free argument as
        # probe_sorted_np).
        with qspan("searchsorted", level=int(lv), queries=int(sel.size)):
            bids = np.searchsorted(
                bl.first_keys, q.astype(np.uint64, copy=False),
                side="right",
            ) - 1
            np.clip(bids, 0, bl.num_blocks - 1, out=bids)
        for b in np.unique(bids):
            # Shared-store read: keyed by the stream's inode identity
            # (see SealedBlockStream.ident), so every reader/route of
            # one DB shares one decoded copy, and an overwrite-swapped
            # DB can never serve the old directory's blocks.
            def _decode(bl=bl, b=int(b), lv=lv):
                t0 = time.perf_counter()
                try:
                    with qspan("block_decode", level=int(lv),
                               block=int(b)):
                        # The fault fires INSIDE the span: an injected
                        # delay here is the slow-decode shape, and the
                        # resulting trace must attribute it to decode.
                        faults.fire("serve.block_decode",
                                    level=int(lv), block=int(b))
                        pair = bl.read_block(b)
                except (BlockCorruptError, OSError) as e:
                    raise DbFormatError(
                        f"{self.dir}: level {lv} block {b} "
                        f"unreadable: {e}"
                    ) from e
                self._m_decode_secs.observe(time.perf_counter() - t0)
                return pair

            loader = _decode
            if self._shm is not None:
                # The cross-worker tier sits UNDER the private store:
                # private miss -> shm probe (a sibling worker's decode,
                # one memcpy) -> real pread+decode, which is then
                # published for the rest of the fleet. Epoch-stamped:
                # a reloaded DB's slots read as misses, never as wrong
                # blocks (store/shm.py).
                def loader(bl=bl, b=int(b), decode=_decode):
                    key = (bl.ident[0], bl.ident[1], int(b))
                    pair = self._shm.get(key, self.epoch)
                    if pair is None:
                        pair = decode()
                        self._shm.put(key, self.epoch, pair[0], pair[1])
                    return pair

            pair, hit = self._store.read_ex((bl.ident, int(b)), loader)
            with self._stats_lock:
                if hit:
                    self._hits += 1
                else:
                    self._misses += 1
            if hit and self._m_cache_hits is not None:
                self._m_cache_hits.inc()
            elif not hit and self._m_cache_misses is not None:
                self._m_cache_misses.inc()
            bkeys, bcells = pair
            bsel = sel[bids == b]
            idx, hit = probe_sorted_np(
                bkeys, canon[bsel].astype(bkeys.dtype, copy=False)
            )
            hsel = bsel[hit]
            if hsel.size:
                v, r = unpack_cells_np(bcells[idx[hit]])
                values[hsel] = v
                remoteness[hsel] = r
                found[hsel] = True

    def lookup_best(self, queries):
        """lookup + the optimal child of each decided, non-terminal query.

        Returns (values, remoteness, found, best [K] state_dtype) where
        best is a packed child of the QUERIED position — a legal move the
        client can actually play, even against a sym=1 database —
        realizing the parent's value (WIN -> a LOSE child of minimum
        remoteness; LOSE -> a WIN child of maximum remoteness, delaying;
        TIE -> a TIE child of maximum remoteness), or the sentinel when
        there is no move (terminal positions, misses). Children are scored
        through their canonical twins in the same probe path.
        """
        q = np.ascontiguousarray(
            np.asarray(queries, dtype=self.game.state_dtype)
        )
        k = q.shape[0]
        sentinel = self.game.sentinel
        best = np.full(k, sentinel, dtype=self.game.state_dtype)
        if k == 0:
            return (
                np.zeros(0, dtype=np.uint8),
                np.zeros(0, dtype=np.int32),
                np.zeros(0, dtype=bool),
                best,
            )
        values, remoteness, found = self._probe(*self._canon_levels(q))
        if not found.any():
            return values, remoteness, found, best
        cap = bucket_size(k, _MIN_QUERY_BUCKET)
        # Expand the RAW queries (see _expand_builder: best must be a legal
        # move of the queried position, while the probe goes through the
        # canonical twins — value/remoteness are sym-invariant).
        raw_children, canon_children, mask, clevels = self._cpu_kernel(
            "dbexpand", cap, _expand_builder, pad_to(q, cap)
        )
        m = raw_children.shape[1]
        children = np.asarray(raw_children).reshape(-1)[: k * m].reshape(k, m)
        mask = np.asarray(mask)[:k]
        cv, cr, cfound = self._probe(
            np.asarray(canon_children)
            .reshape(-1)[: k * m]
            .astype(self.game.state_dtype),
            np.asarray(clevels)[: k * m],
        )
        cv = cv.reshape(k, m)
        cr = cr.reshape(k, m)
        cand_ok = mask & cfound.reshape(k, m)
        big = np.int64(1) << 40  # past any packable remoteness
        for want, prefer_min in ((WIN, True), (LOSE, False), (TIE, False)):
            rows = found & (values == want) & cand_ok.any(axis=1)
            if not rows.any():
                continue
            # WIN wants a LOSE child; LOSE has only WIN children; TIE wants
            # a TIE child (combine_host, solve/oracle.py).
            child_want = {WIN: LOSE, LOSE: WIN, TIE: TIE}[want]
            cand = cand_ok & (cv == child_want)
            rows &= cand.any(axis=1)
            if not rows.any():
                continue
            score = np.where(
                cand, cr.astype(np.int64), big if prefer_min else -big
            )
            pick = (
                score.argmin(axis=1) if prefer_min else score.argmax(axis=1)
            )
            best[rows] = children[np.arange(k), pick][rows]
        return values, remoteness, found, best
