"""Solver heartbeat: periodic liveness + resource records.

A big-board solve is hours of silence between per-level records — longer
than the environment's relay MTBF — and when it wedges the operator has
nothing to distinguish "slow level" from "dead backend". The heartbeat
is a daemon thread that every ``interval`` seconds emits one record with:

* the solver's current progress (phase + level + frontier size — a
  ``progress`` callable supplied by the owner, read without locking:
  the dict is replaced atomically, never mutated in place);
* host RSS (``/proc/self/statm`` when available, ``resource`` else);
* per-device memory stats when the backend exposes them
  (``Device.memory_stats()``; absent on CPU — omitted, never fatal).

Records go to the shared JSONL logger (``{"phase": "heartbeat", ...}``)
and to registry gauges (``gamesman_rss_bytes``,
``gamesman_device_bytes_in_use{device=...}``,
``gamesman_heartbeat_beats_total``), so a wedged solve is visible both
in the artifact file and on a live ``/metrics`` scrape.

Enable via ``Solver(heartbeat_secs=...)``, the ``--heartbeat-secs`` CLI
flag, or ``GAMESMAN_HEARTBEAT_SECS``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from gamesmanmpi_tpu.obs.registry import MetricsRegistry, default_registry


def rss_bytes() -> Optional[int]:
    """Resident set size of this process, None when undeterminable.

    None, not 0: a containerized /proc-less host (or a masked
    ``/proc/self/statm``) is a *measurement* failure, and a heartbeat
    stream full of ``rss_bytes: 0`` reads as "the solver uses no
    memory" — the record carries ``null`` instead and the gauge is
    simply not set. Never raises (the beat must not be able to
    traceback once per interval on an exotic host)."""
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys

        # ru_maxrss: bytes on macOS, KiB on Linux — peak, not current,
        # but a usable fallback where /proc is absent.
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return rss if sys.platform == "darwin" else rss * 1024
    except Exception:  # exotic platforms / faked failures in tests
        return None


def process_rank():
    """This process's rank in a multi-process run, None single-process.

    Reads sys.modules instead of importing jax: the heartbeat must work
    (and stay cheap) in jax-free consumers like the query server, and
    must never be the thing that first initializes a backend — which is
    why an imported-but-untouched jax is ALSO left alone: process_count
    itself triggers backend init, so we only ask once xla_bridge already
    holds a live backend."""
    try:
        import sys

        jax = sys.modules.get("jax")
        xb = sys.modules.get("jax._src.xla_bridge")
        if (jax is not None and xb is not None
                and getattr(xb, "_backends", None)
                and jax.process_count() > 1):
            return int(jax.process_index())
    except Exception:  # uninitialized distributed state: single-process
        pass
    return None


def device_memory_stats() -> dict:
    """{device label: {bytes_in_use, bytes_limit}} for devices that
    report them; {} when jax is unavailable/uninitialized or the backend
    (CPU) has no allocator stats. Never raises: the heartbeat must not
    be able to kill or wedge the solve it is watching."""
    out: dict = {}
    try:
        import jax

        for d in jax.local_devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                continue
            if not stats:
                continue
            rec = {}
            if "bytes_in_use" in stats:
                rec["bytes_in_use"] = int(stats["bytes_in_use"])
            if "bytes_limit" in stats:
                rec["bytes_limit"] = int(stats["bytes_limit"])
            if rec:
                out[f"{d.platform}:{d.id}"] = rec
    except Exception:
        return {}
    return out


class Heartbeat:
    """Periodic progress/RSS/device-memory reporter (daemon thread).

    ``progress``: zero-arg callable returning a dict merged into every
    beat (the solver passes its current phase/level). ``stop()`` joins
    the thread; it is also a context manager. A beat is also emitted at
    stop() time when at least one interval elapsed since the last one,
    so short runs still leave a final resource sample.
    """

    def __init__(self, interval: float, *,
                 progress: Optional[Callable[[], dict]] = None,
                 logger=None,
                 registry: Optional[MetricsRegistry] = None,
                 clock=time.monotonic):
        if interval <= 0:
            raise ValueError("heartbeat interval must be positive")
        self.interval = float(interval)
        self.progress = progress
        self.logger = logger
        self.registry = registry or default_registry()
        self.beats = 0
        self._clock = clock
        self._t0 = clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "Heartbeat":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="gamesman-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ---------------------------------------------------------------- beat

    def beat(self) -> dict:
        """Emit one record now (also callable directly — tests, a final
        sample at stop)."""
        rec: dict = {
            "phase": "heartbeat",
            "uptime_secs": round(self._clock() - self._t0, 3),
            # None (JSON null) when /proc and the resource fallback are
            # both unavailable — a masked /proc must degrade the one
            # field, not traceback every beat (tests fake the failure).
            "rss_bytes": rss_bytes(),
        }
        rank = process_rank()
        if rank is not None:
            # Rank-stamped so N processes' interleaved heartbeat streams
            # stay attributable (docs/DISTRIBUTED.md); single-process
            # records are byte-identical to before.
            rec["rank"] = rank
        if self.progress is not None:
            try:
                # Nested, not merged: the solver's progress dict carries
                # its own "phase" key, which must not masquerade as a
                # per-level record in the shared JSONL stream.
                rec["progress"] = dict(self.progress() or {})
            except Exception:  # the watched solver owns its own errors
                pass
        try:
            dev = device_memory_stats()
        except Exception:  # noqa: BLE001 - belt-and-braces: never a
            dev = {}       # traceback-per-beat, whatever the backend does
        if dev:
            rec["devices"] = dev
        self.beats += 1
        reg = self.registry
        reg.counter(
            "gamesman_heartbeat_beats_total", "heartbeat records emitted"
        ).inc()
        if rec["rss_bytes"] is not None:
            reg.gauge(
                "gamesman_rss_bytes",
                "resident set size of the solver process",
            ).set(rec["rss_bytes"])
        for label, stats in dev.items():
            if "bytes_in_use" in stats:
                reg.gauge(
                    "gamesman_device_bytes_in_use",
                    "per-device allocator bytes in use",
                    device=label,
                ).set(stats["bytes_in_use"])
            if "bytes_limit" in stats:
                reg.gauge(
                    "gamesman_device_bytes_limit",
                    "per-device allocator byte limit",
                    device=label,
                ).set(stats["bytes_limit"])
        if self.logger is not None:
            self.logger.log(rec)
        return rec

    def _run(self) -> None:
        last = self._clock()
        while not self._stop.wait(self.interval):
            self.beat()
            last = self._clock()
        if self._clock() - last >= self.interval:
            self.beat()
