"""Live solve status: ``/status`` + ``/metrics`` served from the solver.

Before this module the only windows into a running solve were the
per-process heartbeat line and post-hoc ``tools/obs_report.py`` — the
blind spot the Pentago solve (arXiv:1404.0743) and the consumer-grade
7x6 Connect-Four solve (arXiv:2507.05267) both had to engineer around
with live per-phase accounting. One read-only stdlib HTTP endpoint per
solver process answers the operator's four questions — where are you,
how fast, what's the bottleneck, when will you finish:

* ``GET /status`` — JSON: game/config, current phase+level, positions
  discovered/solved (monotone), the per-level schedule-based progress
  model with an ETA that converges as backward levels complete,
  io_wait/prefetch/write-behind stats, retries, and (rank 0 of a
  multi-process run) the fleet-merged per-rank view with stragglers
  flagged;
* ``GET /metrics`` — the process registry's Prometheus text exposition,
  exactly what the serving stack already exposes.

Enable with ``GAMESMAN_STATUS_PORT`` / ``--status-port`` (0 = ephemeral;
``GAMESMAN_STATUS_ADDR_FILE`` publishes the bound ``host:port`` for
supervisors — the campaign proxies its child's status through one
stable operator port this way). The server must never be able to kill
or slow the solve it is watching: bind failures degrade to "no status
server" with a warning, handler errors answer 500, and every read is a
snapshot of atomically-replaced dicts — no lock is shared with the
solve thread.
"""

from __future__ import annotations

import json
import os
import socket
import statistics
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.request import urlopen

from gamesmanmpi_tpu.obs.registry import MetricsRegistry, default_registry
from gamesmanmpi_tpu.utils.env import env_float, env_opt, env_str

#: ETA smoothing: weight of the newest completed level's throughput in
#: the running estimate (EWMA — late levels dominate, early compile-
#: polluted levels wash out, so the ETA converges).
_EWMA_ALPHA = 0.4


def status_port_configured() -> Optional[int]:
    """The configured status port, or None (unset/malformed = off).
    0 means "bind an ephemeral port" (used with
    ``GAMESMAN_STATUS_ADDR_FILE`` by supervisors)."""
    raw = env_opt("GAMESMAN_STATUS_PORT")
    if raw is None or not raw.strip():
        return None
    try:
        port = int(raw)
    except ValueError:
        sys.stderr.write(
            f"warning: GAMESMAN_STATUS_PORT={raw!r} is not an integer; "
            "status server disabled\n"
        )
        return None
    return port if port >= 0 else None


def straggler_factor() -> float:
    """A rank is flagged as a straggler when its per-level wall exceeds
    this multiple of the fleet's median for that level."""
    return max(env_float("GAMESMAN_STATUS_STRAGGLER_FACTOR", 1.5), 1.0)


class SolveStatusTracker:
    """The per-solver progress model behind ``/status``.

    Written only by the solve thread (every mutation replaces a dict or
    bumps a scalar — atomic under the GIL, the ``progress`` contract);
    read by HTTP handler threads via :meth:`snapshot`.

    The ETA is level-schedule based: once forward discovery fixes the
    per-level position counts, the remaining backward work is known
    exactly, and the estimate is remaining positions over an EWMA of
    completed backward levels' throughput — so it starts as soon as the
    first level resolves and converges as the sweep proceeds.
    """

    def __init__(self, *, clock=time.monotonic):
        self._clock = clock
        self.t0 = clock()
        self.meta: dict = {}
        #: level -> {"n", "secs"} per phase; replaced, never mutated.
        self.forward_levels: Dict[int, dict] = {}
        self.backward_levels: Dict[int, dict] = {}
        #: level -> positions, fixed when forward completes.
        self.schedule: Dict[int, int] = {}
        self.positions_discovered = 0
        self.positions_solved = 0
        self._ewma_pps: Optional[float] = None

    def begin(self, **meta) -> None:
        """Identity fields echoed into every snapshot (game, engine,
        shards, world, rank, attempt...)."""
        self.meta = {**self.meta, **meta}

    def forward_level(self, level, n, secs) -> None:
        self.forward_levels = {
            **self.forward_levels,
            int(level): {"n": int(n), "secs": round(float(secs or 0.0), 6)},
        }
        self.positions_discovered += int(n)

    def set_schedule(self, schedule: Dict[int, int]) -> None:
        self.schedule = {int(k): int(v) for k, v in schedule.items()}

    def backward_level(self, level, n, secs, resumed: bool = False) -> None:
        secs = float(secs or 0.0)
        self.backward_levels = {
            **self.backward_levels,
            int(level): {"n": int(n), "secs": round(secs, 6)},
        }
        self.positions_solved += int(n)
        # Checkpoint-resumed levels replay millions of positions in
        # milliseconds (loaded, not computed): feeding them into the
        # throughput EWMA would make a restarted run's ETA claim a
        # multi-hour sweep finishes in seconds. They still count as
        # solved work (the ETA numerator shrinks); only the rate model
        # skips them.
        if n and secs > 0 and not resumed:
            pps = int(n) / secs
            self._ewma_pps = (
                pps if self._ewma_pps is None
                else (1 - _EWMA_ALPHA) * self._ewma_pps + _EWMA_ALPHA * pps
            )

    # -------------------------------------------------------------- reading

    def eta_secs(self) -> Optional[float]:
        """Predicted seconds to finish the backward sweep, or None while
        unestimable (no schedule yet / nothing resolved yet)."""
        if not self.schedule or self._ewma_pps is None:
            return None
        done = self.backward_levels
        remaining = sum(
            n for k, n in self.schedule.items() if k not in done
        )
        if remaining <= 0:
            return 0.0
        return round(remaining / max(self._ewma_pps, 1e-9), 3)

    def snapshot(self, progress: Optional[dict] = None) -> dict:
        fwd, bwd = self.forward_levels, self.backward_levels
        levels = {}
        for k in sorted(set(fwd) | set(bwd)):
            row: dict = {}
            if k in fwd:
                row["n"] = fwd[k]["n"]
                row["fwd_secs"] = fwd[k]["secs"]
            if k in bwd:
                row["n"] = bwd[k]["n"]
                row["bwd_secs"] = bwd[k]["secs"]
            levels[str(k)] = row
        snap = {
            **self.meta,
            "uptime_secs": round(self._clock() - self.t0, 3),
            "phase": (progress or {}).get("phase"),
            "level": (progress or {}).get("level"),
            "positions_discovered": self.positions_discovered,
            "positions_solved": self.positions_solved,
            "levels_total": len(self.schedule) or None,
            "levels_solved": len(bwd),
            "throughput_pps": (
                round(self._ewma_pps, 1) if self._ewma_pps else None
            ),
            "eta_secs": self.eta_secs(),
            "levels": levels,
        }
        return snap


# --------------------------------------------------------------- the server


class _StatusHandler(BaseHTTPRequestHandler):
    server_version = "gamesman-status/1"
    protocol_version = "HTTP/1.1"
    timeout = 30

    def log_message(self, fmt, *args):  # quiet: one scrape/s is not news
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True

    def do_GET(self):  # noqa: N802 - http.server API
        srv = self.server
        route = self.path.partition("?")[0]
        srv.registry.counter(
            "gamesman_status_requests_total",
            "GET requests answered by the live status endpoint",
            # Bounded label set: a port scanner walking a wordlist must
            # not mint one permanent registry series per probed path.
            path=route if route in ("/status", "/metrics") else "other",
        ).inc()
        if route == "/status":
            try:
                body = json.dumps(srv.provider(), default=str).encode()
                self._send(200, body, "application/json")
            except Exception as e:  # noqa: BLE001 - must not kill the solve
                self._send(
                    500,
                    json.dumps({"error": f"{type(e).__name__}: {e}"})
                    .encode(),
                    "application/json",
                )
        elif route == "/metrics":
            self._send(
                200, srv.registry.render_prometheus().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        else:
            self._send(
                404,
                json.dumps({"error": f"no such path {self.path!r}"})
                .encode(),
                "application/json",
            )


class _StatusHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, provider, registry):
        super().__init__(addr, _StatusHandler)
        self.provider = provider
        self.registry = registry


class StatusServer:
    """Read-only status endpoint for one process (daemon thread).

    ``provider`` is a zero-arg callable returning the ``/status`` body;
    it runs on handler threads, so it must only read atomically-replaced
    state (the tracker/progress contract). ``addr_file`` (optional)
    publishes the bound ``host:port`` atomically for supervisors.
    """

    def __init__(self, provider: Callable[[], dict], *,
                 port: int = 0, host: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 addr_file=None):
        if host is None:
            # Bind this host (GAMESMAN_STATUS_HOST): on a real
            # multi-host run each rank must announce an address its
            # peers can actually reach, not loopback — same reason the
            # retry coordinator's host is configurable.
            host = env_str("GAMESMAN_STATUS_HOST", "127.0.0.1")
        self._http = _StatusHTTPServer(
            (host, int(port)), provider, registry or default_registry()
        )
        self.host = host
        self.port = self._http.server_address[1]
        # Advertised address != bind address for wildcard binds: a rank
        # announcing "0.0.0.0:<port>" would make every peer (and the
        # addr-file reader) dial its OWN loopback — derive a reachable
        # name instead.
        adv = host
        if host in ("", "0.0.0.0", "::"):
            try:
                adv = socket.gethostname() or "127.0.0.1"
            except OSError:
                adv = "127.0.0.1"
        self.address = f"{adv}:{self.port}"
        self._thread: Optional[threading.Thread] = None
        if addr_file:
            tmp = f"{addr_file}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                fh.write(self.address)
            os.replace(tmp, addr_file)

    def start(self) -> "StatusServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._http.serve_forever,
                name="gamesman-status", daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "StatusServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def maybe_status_server(provider, *, registry=None,
                        rank: int = 0, world: int = 1,
                        ) -> Optional[StatusServer]:
    """Env-gated status server: ``GAMESMAN_STATUS_PORT`` unset = off.

    Multi-process runs offset a nonzero base port by rank (rank i binds
    port+i — the convention the fleet scraper falls back to); rank 0
    alone honors ``GAMESMAN_STATUS_ADDR_FILE`` so N ranks never race
    onto one file. A bind failure warns and returns None — the status
    plane must never abort a solve.
    """
    port = status_port_configured()
    if port is None:
        return None
    if port > 0 and world > 1:
        port = port + int(rank)
    addr_file = env_opt("GAMESMAN_STATUS_ADDR_FILE") if rank == 0 else None
    try:
        return StatusServer(
            provider, port=port, registry=registry, addr_file=addr_file
        ).start()
    except (OSError, OverflowError) as e:
        # OverflowError: an out-of-range port (typo, or a high base plus
        # the rank offset walking past 65535) raises it from bind() —
        # it must degrade like any other bind failure, not abort a
        # multi-hour solve at startup.
        sys.stderr.write(
            f"warning: status server failed to bind port {port} ({e}); "
            "continuing without /status\n"
        )
        return None


# ------------------------------------------------------- fleet aggregation


def fetch_status(address: str, timeout: Optional[float] = None,
                 ) -> Optional[dict]:
    """GET ``http://<address>/status`` -> dict, or None on any failure
    (a dead peer must degrade the fleet view, not the scrape)."""
    if timeout is None:
        timeout = env_float("GAMESMAN_STATUS_SCRAPE_TIMEOUT", 2.0)
    try:
        with urlopen(f"http://{address}/status", timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except Exception:  # noqa: BLE001 - peer death is a normal condition
        return None


def _level_walls(snap: dict) -> Dict[int, float]:
    """level -> this rank's wall seconds (forward + backward)."""
    out: Dict[int, float] = {}
    for k, row in (snap.get("levels") or {}).items():
        try:
            lvl = int(k)
        except (TypeError, ValueError):
            continue
        out[lvl] = (float(row.get("fwd_secs") or 0.0)
                    + float(row.get("bwd_secs") or 0.0))
    return out


def merge_fleet(rank_snaps: Dict[int, dict], *, world: int,
                factor: Optional[float] = None) -> dict:
    """Fold per-rank ``/status`` snapshots into the fleet view rank 0
    serves: per-level wall = MAX across ranks (the level ran once,
    collectively — same rule as tools/obs_report.py), per-rank progress
    summaries, and stragglers — ranks whose wall for some level exceeds
    ``factor`` x the fleet median for that level."""
    if factor is None:
        factor = straggler_factor()
    walls = {r: _level_walls(s) for r, s in rank_snaps.items()}
    levels: Dict[int, dict] = {}
    for r, per in walls.items():
        for lvl, w in per.items():
            row = levels.setdefault(lvl, {"wall_secs": 0.0, "by_rank": {}})
            row["wall_secs"] = max(row["wall_secs"], w)
            row["by_rank"][str(r)] = round(w, 6)
    stragglers: Dict[int, dict] = {}
    for lvl, row in levels.items():
        vals = [w for w in row["by_rank"].values() if w > 0]
        if len(vals) < 2:
            continue
        med = statistics.median(vals)
        if med <= 0:
            continue
        for r, w in row["by_rank"].items():
            if w > factor * med:
                cur = stragglers.get(int(r))
                if cur is None or w / med > cur["lag"]:
                    stragglers[int(r)] = {
                        "rank": int(r), "level": lvl,
                        "wall_secs": round(w, 6),
                        "median_secs": round(med, 6),
                        "lag": round(w / med, 3),
                    }
    etas = [
        s.get("eta_secs") for s in rank_snaps.values()
        if isinstance(s.get("eta_secs"), (int, float))
    ]
    return {
        "world": int(world),
        "ranks_reporting": sorted(rank_snaps),
        "ranks": {
            str(r): {
                k: s.get(k)
                for k in ("phase", "level", "positions_solved",
                          "positions_discovered", "eta_secs",
                          "throughput_pps", "uptime_secs")
            }
            for r, s in sorted(rank_snaps.items())
        },
        "levels": {
            str(k): {"wall_secs": round(v["wall_secs"], 6),
                     "by_rank": v["by_rank"]}
            for k, v in sorted(levels.items())
        },
        "stragglers": [stragglers[r] for r in sorted(stragglers)],
        "straggler_factor": factor,
        "eta_secs": max(etas) if etas else None,
    }
