"""SLO objectives + multi-window burn-rate computation for serving.

Aggregate histograms say *how* the fleet performed; an SLO says whether
that performance is *acceptable*, and a burn rate says how fast the
error budget is being spent. Two declared objectives per route:

* **availability** — a request is bad when it errored (5xx) or was shed
  (503 under overload/breaker). Target ``GAMESMAN_SLO_AVAIL_TARGET``
  (default 0.999 → budget 0.1%).
* **latency** — a request is bad when it took longer than
  ``GAMESMAN_SLO_P99_MS`` (default 250 ms, matching the BENCH_SERVE
  gate). Target ``GAMESMAN_SLO_LATENCY_TARGET`` (default 0.99 → budget
  1%: the p99 objective spelled as a ratio SLO).

Burn rate = (bad fraction over a window) / error budget: 1.0 means the
budget is being spent exactly at the rate that exhausts it at the
window's end; 14.4 over a short window is the classic "page now"
threshold (Google SRE workbook, ch. 5). Two windows are computed —
fast (``GAMESMAN_SLO_FAST_WINDOW_SECS``, default 300) and slow
(``GAMESMAN_SLO_SLOW_WINDOW_SECS``, default 3600) — from a ring of
per-second good/bad buckets, so memory is bounded and the fast window
recovers quickly once the bad minute ends. A fast-window burn above
``GAMESMAN_SLO_FAST_BURN`` (default 14.4) with at least
``GAMESMAN_SLO_MIN_REQUESTS`` requests in the window flips
``fast_burn`` for that
objective; the server folds any tripped objective into its ``/healthz``
status as ``degraded``, which the fleet supervisor already propagates
(a degraded worker beat degrades fleet ``/status``) — the fleet goes
amber *before* the budget is gone, not after.

All observation goes through ``observe()`` on the request path (one
lock, two bucket increments); burn rates are derived at read time
(``snapshot()``), which is when the gauges are refreshed too.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, Optional

from gamesmanmpi_tpu.obs.registry import MetricsRegistry, default_registry
from gamesmanmpi_tpu.utils.env import env_float

#: Registry families the SLO engine records into.
SLO_REQUESTS = "gamesman_slo_requests_total"
SLO_BURN_RATE = "gamesman_slo_burn_rate"
SLO_FAST_BURN = "gamesman_slo_fast_burn"
SLO_FAST_BURN_TRIPS = "gamesman_slo_fast_burn_trips_total"

#: Good/bad accounting granularity (seconds per bucket). One second so
#: a test can shrink the fast window to a few seconds and still watch
#: the burn rate rise AND recover; memory stays O(slow_window) cells.
BUCKET_SECS = 1.0

#: The two declared objectives, in snapshot order.
OBJECTIVES = ("availability", "latency")


class _Window:
    """Ring of (bucket_start, good, bad) for one (route, objective)."""

    __slots__ = ("buckets",)

    def __init__(self):
        self.buckets: "OrderedDict[int, list]" = OrderedDict()

    def add(self, now: float, good: int, bad: int, horizon: float) -> None:
        key = int(now // BUCKET_SECS)
        cell = self.buckets.get(key)
        if cell is None:
            cell = self.buckets[key] = [0, 0]
        cell[0] += good
        cell[1] += bad
        # Prune past the slow horizon; the ring stays O(horizon / 10s).
        floor = key - int(horizon // BUCKET_SECS) - 1
        while self.buckets:
            k = next(iter(self.buckets))
            if k >= floor:
                break
            del self.buckets[k]

    def totals(self, now: float, window: float):
        """(good, bad) over the trailing ``window`` seconds."""
        floor = int((now - window) // BUCKET_SECS)
        good = bad = 0
        for k, (g, b) in self.buckets.items():
            if k > floor:
                good += g
                bad += b
        return good, bad


class SloEngine:
    """Per-route availability + latency objectives with fast/slow
    burn-rate windows. One engine per server; thread-safe."""

    def __init__(self, *, p99_ms: Optional[float] = None,
                 avail_target: Optional[float] = None,
                 latency_target: Optional[float] = None,
                 fast_window: Optional[float] = None,
                 slow_window: Optional[float] = None,
                 fast_burn: Optional[float] = None,
                 min_requests: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock=time.time):
        self.p99_ms = float(
            p99_ms if p99_ms is not None
            else env_float("GAMESMAN_SLO_P99_MS", 250.0)
        )
        self.targets = {
            "availability": float(
                avail_target if avail_target is not None
                else env_float("GAMESMAN_SLO_AVAIL_TARGET", 0.999)
            ),
            "latency": float(
                latency_target if latency_target is not None
                else env_float("GAMESMAN_SLO_LATENCY_TARGET", 0.99)
            ),
        }
        self.fast_window = float(
            fast_window if fast_window is not None
            else env_float("GAMESMAN_SLO_FAST_WINDOW_SECS", 300.0)
        )
        self.slow_window = max(self.fast_window, float(
            slow_window if slow_window is not None
            else env_float("GAMESMAN_SLO_SLOW_WINDOW_SECS", 3600.0)
        ))
        self.fast_burn_threshold = float(
            fast_burn if fast_burn is not None
            else env_float("GAMESMAN_SLO_FAST_BURN", 14.4)
        )
        # Volume gate: with a 0.1% availability budget a SINGLE bad
        # request among ten is a 100x burn — statistically meaningless.
        # fast_burn only trips once the fast window holds this many
        # requests (burn rates themselves are always reported).
        self.min_requests = max(1, int(
            min_requests if min_requests is not None
            else env_float("GAMESMAN_SLO_MIN_REQUESTS", 100)
        ))
        self._registry = registry or default_registry()
        self._clock = clock
        self._lock = threading.Lock()
        # (route, objective) -> _Window
        self._windows: Dict[tuple, _Window] = {}
        # (route, objective) -> currently tripped?  (edge-detects trips)
        self._tripped: Dict[tuple, bool] = {}

    # ------------------------------------------------------------ writes

    def observe(self, route: str, secs: float, code: int,
                *, shed: bool = False) -> None:
        """One finished request. ``shed`` marks load-shedding 503s
        (breaker/overload/drain) — bad for availability even though the
        status code is intentional."""
        now = self._clock()
        bad_avail = bool(shed or int(code) >= 500)
        bad_latency = (secs * 1e3) > self.p99_ms
        with self._lock:
            for obj, bad in (("availability", bad_avail),
                             ("latency", bad_latency)):
                win = self._windows.get((route, obj))
                if win is None:
                    win = self._windows[(route, obj)] = _Window()
                win.add(now, 0 if bad else 1, 1 if bad else 0,
                        self.slow_window)
                self._registry.counter(
                    SLO_REQUESTS,
                    "requests per SLO objective by good/bad outcome",
                    route=route, slo=obj,
                    outcome="bad" if bad else "good",
                ).inc()

    # ------------------------------------------------------------- reads

    def _burn(self, win: _Window, now: float, window: float,
              budget: float) -> float:
        good, bad = win.totals(now, window)
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / max(budget, 1e-9)

    def snapshot(self) -> dict:
        """Per-route burn rates + fast-burn flags; refreshes the
        ``gamesman_slo_*`` gauges as a side effect (read-time derivation:
        the request path never computes a burn rate)."""
        now = self._clock()
        out: dict = {
            "p99_ms": self.p99_ms,
            "fast_window_secs": self.fast_window,
            "slow_window_secs": self.slow_window,
            "fast_burn_threshold": self.fast_burn_threshold,
            "routes": {},
            "fast_burn": False,
        }
        with self._lock:
            keys = list(self._windows.items())
        for (route, obj), win in keys:
            budget = 1.0 - self.targets[obj]
            fast = self._burn(win, now, self.fast_window, budget)
            slow = self._burn(win, now, self.slow_window, budget)
            good, bad = win.totals(now, self.fast_window)
            tripped = (
                fast > self.fast_burn_threshold
                and (good + bad) >= self.min_requests
            )
            route_view = out["routes"].setdefault(route, {})
            route_view[obj] = {
                "target": self.targets[obj],
                "burn_fast": round(fast, 4),
                "burn_slow": round(slow, 4),
                "fast_burn": tripped,
            }
            if tripped:
                out["fast_burn"] = True
            self._registry.gauge(
                SLO_BURN_RATE, "SLO error-budget burn rate per window",
                route=route, slo=obj, window="fast",
            ).set(fast)
            self._registry.gauge(
                SLO_BURN_RATE, "SLO error-budget burn rate per window",
                route=route, slo=obj, window="slow",
            ).set(slow)
            self._registry.gauge(
                SLO_FAST_BURN,
                "1 when the fast-window burn rate exceeds its threshold",
                route=route, slo=obj,
            ).set(1.0 if tripped else 0.0)
            with self._lock:
                was = self._tripped.get((route, obj), False)
                self._tripped[(route, obj)] = tripped
            if tripped and not was:
                self._registry.counter(
                    SLO_FAST_BURN_TRIPS,
                    "fast-burn threshold crossings (edge-triggered)",
                    route=route, slo=obj,
                ).inc()
        return out

    def fast_burning(self) -> bool:
        """True when any (route, objective) is past fast-burn right now
        (the health_status() hook)."""
        return bool(self.snapshot()["fast_burn"])
