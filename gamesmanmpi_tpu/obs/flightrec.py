"""Flight recorder: a bounded in-memory ring of recent solve events.

A multi-hour campaign attempt that dies with exit 124 (watchdog,
collective deadline), a crash, or a SIGKILL leaves log tails and a
checkpoint prefix — but not the *sequence of recent events* that led to
the death: which spans were in flight, which levels had just sealed,
which retries and faults fired, which store I/O was pending. Rerunning
under instrumentation to find out costs hours. The flight recorder is
the always-on answer (the same discipline the Pentago solve,
arXiv:1404.0743, applied with per-phase instrumentation at scale): every
process keeps a cheap ring of its last ``GAMESMAN_FLIGHTREC_EVENTS``
events, and every abnormal exit path dumps it as
``flightrec_<rank>.json``:

* the watchdog's stall abort (resilience/supervisor.py);
* the preemption grace deadline (resilience/preempt.py) and the CLI's
  preempted/oom/coordinated-abort/crash handlers;
* the sharded collective-deadline abort (parallel/sharded.py);
* the campaign supervisor's death classifier (resilience/campaign.py,
  rank ``campaign``).

A SIGKILL leaves no in-process exit path at all, so the engines also
checkpoint the ring at every level boundary (``boundary``) when
``GAMESMAN_FLIGHTREC_DIR`` is set — the campaign sets it for every
attempt, so even ``kill -9`` leaves a post-mortem naming the last
completed level and the spans that were in flight at the last boundary.

Cost discipline: events are recorded at span/level/retry/fault/store
rates (host-side, a handful per level), never per position; a record is
one lock acquisition and one deque append. Dumps are tmp+``os.replace``
(atomic — a dump torn by the death it is recording would be worthless).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

from gamesmanmpi_tpu.utils.env import env_int, env_opt

#: Default ring capacity (events). Override: GAMESMAN_FLIGHTREC_EVENTS.
DEFAULT_EVENTS = 2048


def _clean_fields(fields: dict) -> dict:
    """JSON-safe scalars only (numpy ints arrive via span payloads)."""
    out = {}
    for k, v in fields.items():
        if isinstance(v, bool) or v is None or isinstance(v, str):
            out[k] = v
        elif isinstance(v, (int, float)):
            out[k] = v
        else:
            try:
                out[k] = int(v)
            except (TypeError, ValueError):
                out[k] = str(v)
    return out


class FlightRecorder:
    """One process's ring of recent events + in-flight span table.

    Thread-safe: the solve thread, span exits, retry/fault hooks, and
    the store's background workers all record concurrently; dumps run
    on whatever thread is dying (watchdog, deadline timer, main).
    NEVER call from a signal handler — ``record`` takes the ring lock
    (the GM205 rule); the dump paths all run on ordinary threads.
    """

    def __init__(self, capacity: Optional[int] = None, *, clock=time.time):
        if capacity is None:
            capacity = env_int("GAMESMAN_FLIGHTREC_EVENTS", DEFAULT_EVENTS)
        self.capacity = max(int(capacity), 16)
        self._clock = clock
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(
            maxlen=self.capacity
        )  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock
        #: sid -> (name, t0, fields) of spans begun but not ended.
        self._inflight: dict = {}  # guarded-by: _lock
        #: phase -> deepest/last level completed (the headline a
        #: post-mortem reader wants first).
        self._last_completed: dict = {}  # guarded-by: _lock

    # ------------------------------------------------------------ recording

    def record(self, kind: str, /, **fields) -> None:
        # Positional-only `kind` + rename-on-collision: span payloads
        # legitimately carry their own "kind" field (checkpoint spans'
        # kind=frontier|level) which must not clobber the event kind.
        ev = {"t": round(self._clock(), 6), "kind": str(kind)}
        for k, v in _clean_fields(fields).items():
            if k in ("t", "kind"):
                k = f"field_{k}"
            ev[k] = v
        with self._lock:
            if len(self._events) == self.capacity:
                self._dropped += 1
            self._events.append(ev)

    def span_begin(self, sid: int, name: str, fields: dict) -> None:
        # COPY the fields: the span owner keeps mutating its dict via
        # .set()/end(**fields) with no lock shared with the recorder —
        # snapshotting a live dict mid-mutation can raise, and a dump
        # runs on dying-path threads that must reach their os._exit.
        with self._lock:
            self._inflight[sid] = (str(name), self._clock(), dict(fields))

    def span_end(self, sid: int, name: str, secs: float,
                 fields: dict) -> None:
        with self._lock:
            self._inflight.pop(sid, None)
        payload = {
            k: v for k, v in _clean_fields(fields).items()
            if k not in ("span", "secs")
        }
        self.record("span", span=str(name), secs=round(float(secs), 6),
                    **payload)

    def level_complete(self, phase: str, level) -> None:
        """A level boundary passed: remember it (the dump's headline)
        and ring-record it."""
        with self._lock:
            self._last_completed = {
                **self._last_completed, phase: int(level),
            }
        self.record("level", phase=phase, level=int(level))

    # -------------------------------------------------------------- reading

    def snapshot(self) -> dict:
        now = self._clock()
        with self._lock:
            events = list(self._events)
            inflight = [
                {
                    "span": name,
                    "age_secs": round(now - t0, 6),
                    **_clean_fields(dict(fields)),
                }
                for name, t0, fields in self._inflight.values()
            ]
            return {
                "capacity": self.capacity,
                "dropped": self._dropped,
                "last_completed": dict(self._last_completed),
                "inflight_spans": inflight,
                "events": events,
            }

    def dump(self, reason: str, directory=None,
             rank=None) -> Optional[str]:
        """Write ``flightrec_<rank>.json`` (atomic) into ``directory``
        (default: ``GAMESMAN_FLIGHTREC_DIR``). With neither an explicit
        directory nor the env var the dump is a no-op: a crashing
        ad-hoc solve with no checkpoint dir must not litter the cwd
        (the CLI defaults the env var to the checkpoint directory, the
        campaign to its log dir). Returns the path, or None — a
        post-mortem writer must never add its own crash to the one it
        is recording."""
        # The WHOLE dump is never-raise, snapshot included: the callers
        # are forced-exit paths (watchdog, collective deadline, grace
        # deadline) where an escaped exception would cancel the
        # os._exit they guarantee and leave a wedged rank behind.
        try:
            if directory is None:
                directory = env_opt("GAMESMAN_FLIGHTREC_DIR")
                if not directory:
                    return None
            if rank is None:
                rank = env_opt("GAMESMAN_PROCESS_ID") or "0"
            body = {
                "reason": str(reason),
                "wall_time": time.time(),
                "pid": os.getpid(),
                "rank": str(rank),
                **self.snapshot(),
            }
            path = os.path.join(str(directory), f"flightrec_{rank}.json")
            # Thread-unique tmp: a boundary dump on the solve thread and
            # a deadline/watchdog dump on a timer thread may run
            # concurrently — sharing one tmp name would tear the very
            # post-mortem the atomic replace exists to protect.
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        except Exception:  # noqa: BLE001 - post-mortem writer only
            return None
        try:
            os.makedirs(str(directory), exist_ok=True)
            with open(tmp, "w") as fh:
                json.dump(body, fh, default=str)
            os.replace(tmp, path)
            return path
        except (OSError, TypeError, ValueError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None


_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Optional[FlightRecorder] = None


def default_recorder() -> FlightRecorder:
    """The process-wide recorder every hook records into (capacity read
    from the env at first use; tests construct their own instances)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = FlightRecorder()
        return _DEFAULT


def record(kind: str, **fields) -> None:
    default_recorder().record(kind, **fields)


def dump(reason: str, directory=None, rank=None) -> Optional[str]:
    return default_recorder().dump(reason, directory=directory, rank=rank)


def boundary(phase: str, level) -> None:
    """Level-boundary hook the engines call where ``progress`` is
    replaced: notes the completed level, and — when
    ``GAMESMAN_FLIGHTREC_DIR`` is set (the campaign sets it per
    attempt) — checkpoints the ring to disk so even a SIGKILL leaves a
    post-mortem from the last boundary."""
    rec = default_recorder()
    rec.level_complete(phase, level)
    if env_opt("GAMESMAN_FLIGHTREC_DIR"):
        rec.dump("boundary")
