"""MetricsRegistry: counters, gauges, bucketed histograms.

One registry is a flat namespace of metric FAMILIES; a family has a
name, a help string, a kind, and one child per distinct label set (the
Prometheus data model, stdlib-only). All mutation goes through a single
registry lock — these are bookkeeping increments on host code paths
(request handling, per-level phase boundaries), never per-position work,
so one lock is simpler than per-child atomics and cheap at the call
rates involved.

Two read forms:

* ``snapshot()`` — plain nested dict, the JSON side (``/metrics.json``,
  ``--metrics-out``).
* ``render_prometheus()`` — text exposition format v0.0.4, the form
  Prometheus/curl consume from ``GET /metrics``. Histograms render the
  spec's cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``;
  ``le`` boundaries are INCLUSIVE (a sample equal to a boundary lands in
  that bucket).

``default_registry()`` returns the process-wide singleton. Components
default to it so a solve and the server that later serves its DB land in
one exposition without plumbing; tests wanting isolation construct their
own ``MetricsRegistry`` and pass it explicitly.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Iterable, Optional, Tuple

# Span/latency default buckets: sub-millisecond serving probes up to
# multi-minute solve levels (seconds).
DEFAULT_TIME_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

# Size-ish default buckets (batch sizes, queue depths): powers of 4.
DEFAULT_SIZE_BUCKETS = (
    1.0, 4.0, 16.0, 64.0, 256.0, 1024.0, 4096.0, 16384.0, 65536.0,
)


def _check_name(name: str) -> str:
    if not name or not all(c.isalnum() or c in "_:" for c in name):
        raise ValueError(f"invalid metric name {name!r}")
    if name[0].isdigit():
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _labels_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label_value(s: str) -> str:
    return (
        s.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _format_value(v: float) -> str:
    """Prometheus value spelling: integral floats print as integers
    (counter increments stay readable), +Inf/NaN in Go spellings."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _format_le(b: float) -> str:
    return "+Inf" if math.isinf(b) else _format_value(b)


class _Child:
    """Common base: one (family, label set) instrument."""

    __slots__ = ("_family", "_labels")

    def __init__(self, family: "_Family", labels: Tuple[Tuple[str, str], ...]):
        self._family = family
        self._labels = labels


class Counter(_Child):
    """Monotonic accumulator. ``inc(n)`` with n >= 0."""

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        reg = self._family.registry
        with reg._lock:
            self._family.values[self._labels] = (
                self._family.values.get(self._labels, 0.0) + amount
            )

    @property
    def value(self) -> float:
        with self._family.registry._lock:
            return self._family.values.get(self._labels, 0.0)


class Gauge(_Child):
    """Set-to-current-value instrument (RSS, queue depth, start time)."""

    def set(self, value: float) -> None:
        with self._family.registry._lock:
            self._family.values[self._labels] = float(value)

    def inc(self, amount: float = 1.0) -> None:
        reg = self._family.registry
        with reg._lock:
            self._family.values[self._labels] = (
                self._family.values.get(self._labels, 0.0) + amount
            )

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._family.registry._lock:
            return self._family.values.get(self._labels, 0.0)


class Histogram(_Child):
    """Bucketed distribution. Buckets are per-FAMILY (the exposition
    format requires one boundary set per family); ``observe`` finds the
    first bucket whose inclusive upper bound holds the sample."""

    def observe(self, value: float,
                exemplar: Optional[dict] = None) -> None:
        fam = self._family
        value = float(value)
        with fam.registry._lock:
            counts, total, count = fam.values.get(
                self._labels, (None, 0.0, 0)
            )
            if counts is None:
                counts = [0] * len(fam.buckets)
            for i, b in enumerate(fam.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            fam.values[self._labels] = (counts, total + value, count + 1)
            if exemplar:
                # OpenMetrics-style exemplar: last-write-wins per child
                # (the serving path attaches the trace id of the most
                # recent slow observation, which is exactly the one an
                # operator wants to chase). Rides snapshot() and the
                # openmetrics render; the default v0.0.4 exposition is
                # untouched.
                fam.exemplars[self._labels] = {
                    "labels": {str(k): str(v)
                               for k, v in exemplar.items()},
                    "value": value,
                    "ts": fam.registry._clock(),
                }

    @property
    def count(self) -> int:
        with self._family.registry._lock:
            got = self._family.values.get(self._labels)
            return 0 if got is None else got[2]

    @property
    def sum(self) -> float:
        with self._family.registry._lock:
            got = self._family.values.get(self._labels)
            return 0.0 if got is None else got[1]


class _Family:
    __slots__ = ("registry", "name", "help", "kind", "buckets", "values",
                 "children", "exemplars")

    def __init__(self, registry, name, help_, kind, buckets=None):
        self.registry = registry
        self.name = name
        self.help = help_
        self.kind = kind  # "counter" | "gauge" | "histogram"
        #: histogram boundaries, always ending in +Inf; None otherwise
        self.buckets = buckets
        self.values: dict = {}  # guarded-by: _lock (the registry's)
        self.children: dict = {}  # guarded-by: _lock (the registry's)
        #: label-key -> last exemplar dict; histograms only
        self.exemplars: dict = {}  # guarded-by: _lock (the registry's)


class MetricsRegistry:
    """Thread-safe registry of metric families.

    ``counter``/``gauge``/``histogram`` get-or-create: the first call
    fixes the family's help text (and a histogram's buckets); later
    calls with a different kind raise — one name, one meaning, per
    process."""

    def __init__(self, *, clock=time.time):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}  # guarded-by: _lock
        self._constant_labels: Dict[str, str] = {}  # guarded-by: _lock
        self._clock = clock

    def set_constant_labels(self, **labels) -> None:
        """Labels stamped onto every child created afterwards (explicit
        per-call labels win on collision). The multi-process rank label
        rides here: one call after jax.distributed.initialize and every
        ``gamesman_*`` series this process emits carries
        ``rank="<process_index>"`` — call sites stay unchanged, and a
        single-process run's exposition is byte-identical to before."""
        with self._lock:
            self._constant_labels.update(
                {str(k): str(v) for k, v in labels.items()}
            )

    # -------------------------------------------------------- registration

    def _family(self, name: str, help_: str, kind: str,
                buckets=None) -> _Family:
        _check_name(name)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(
                    self, name, help_, kind, buckets
                )
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"not {kind}"
                )
            return fam

    def _child(self, fam: _Family, labels: dict, cls):
        with self._lock:
            if self._constant_labels:
                labels = {**self._constant_labels, **labels}
            key = _labels_key(labels)
            for k, _ in key:
                _check_name(k)
            child = fam.children.get(key)
            if child is None:
                child = fam.children[key] = cls(fam, key)
                # Seed zero at registration (the standard client-library
                # behavior): a scrape taken before the first write must
                # show 0, not "no data" — an error-rate alert cannot
                # distinguish an unseeded counter from a counter reset.
                if fam.kind == "histogram":
                    fam.values.setdefault(
                        key, ([0] * len(fam.buckets), 0.0, 0)
                    )
                else:
                    fam.values.setdefault(key, 0.0)
            return child

    def counter(self, name: str, help_: str = "", **labels) -> Counter:
        return self._child(
            self._family(name, help_, "counter"), labels, Counter
        )

    def gauge(self, name: str, help_: str = "", **labels) -> Gauge:
        return self._child(self._family(name, help_, "gauge"), labels, Gauge)

    def histogram(self, name: str, help_: str = "",
                  buckets: Optional[Iterable[float]] = None,
                  **labels) -> Histogram:
        # The whole get-or-create must hold the (reentrant) lock: a
        # racing first-registration pair would otherwise both miss the
        # family check and disagree about the bucket set.
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                bounds = sorted(
                    float(b)
                    for b in (buckets if buckets is not None
                              else DEFAULT_TIME_BUCKETS)
                )
                if not bounds:
                    raise ValueError("histogram needs at least one bucket")
                if not math.isinf(bounds[-1]):
                    bounds.append(math.inf)
                fam = self._family(name, help_, "histogram", tuple(bounds))
            elif fam.kind != "histogram":
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    "not histogram"
                )
            return self._child(fam, labels, Histogram)

    # -------------------------------------------------------------- reading

    def snapshot(self) -> dict:
        """Plain-dict view: {name: {type, help, values: [...]}}; histogram
        values carry NON-cumulative per-bucket counts plus sum/count."""
        out: dict = {}
        with self._lock:
            for name, fam in sorted(self._families.items()):
                rows = []
                for key in sorted(fam.values):
                    labels = dict(key)
                    if fam.kind == "histogram":
                        counts, total, count = fam.values[key]
                        rows.append({
                            "labels": labels,
                            "buckets": {
                                _format_le(b): c
                                for b, c in zip(fam.buckets, counts)
                            },
                            "sum": total,
                            "count": count,
                            # Estimated quantiles (bucket interpolation):
                            # the one derivation site — /status payloads,
                            # obs tooling, and tests read these instead of
                            # re-deriving from the raw buckets.
                            "quantiles": {
                                _quantile_key(q): v
                                for q, v in estimate_quantiles(
                                    fam.buckets, counts
                                ).items()
                            },
                        })
                        ex = fam.exemplars.get(key)
                        if ex is not None:
                            rows[-1]["exemplar"] = dict(ex)
                    else:
                        rows.append(
                            {"labels": labels, "value": fam.values[key]}
                        )
                out[name] = {
                    "type": fam.kind, "help": fam.help, "values": rows,
                }
        return out

    def render_prometheus(self) -> str:
        """Text exposition format v0.0.4."""
        lines: list[str] = []
        with self._lock:
            for name, fam in sorted(self._families.items()):
                if fam.help:
                    lines.append(f"# HELP {name} {_escape_help(fam.help)}")
                lines.append(f"# TYPE {name} {fam.kind}")
                for key in sorted(fam.values):
                    if fam.kind == "histogram":
                        counts, total, count = fam.values[key]
                        cum = 0
                        for b, c in zip(fam.buckets, counts):
                            cum += c
                            lines.append(
                                _sample(
                                    name + "_bucket",
                                    key + (("le", _format_le(b)),),
                                    cum,
                                )
                            )
                        lines.append(_sample(name + "_sum", key, total))
                        lines.append(_sample(name + "_count", key, count))
                    else:
                        lines.append(_sample(name, key, fam.values[key]))
        return "\n".join(lines) + ("\n" if lines else "")

    def render_openmetrics(self) -> str:
        """OpenMetrics-style exposition: the v0.0.4 body plus histogram
        bucket exemplars (``# {trace_id="..."} value ts`` on the first
        bucket whose boundary holds the exemplar value) and the
        mandatory ``# EOF`` trailer. Served from ``GET /metrics`` only
        under ``Accept: application/openmetrics-text`` — the default
        exposition stays byte-identical to before exemplars existed."""
        lines: list[str] = []
        with self._lock:
            for name, fam in sorted(self._families.items()):
                if fam.help:
                    lines.append(f"# HELP {name} {_escape_help(fam.help)}")
                lines.append(f"# TYPE {name} {fam.kind}")
                for key in sorted(fam.values):
                    if fam.kind == "histogram":
                        counts, total, count = fam.values[key]
                        ex = fam.exemplars.get(key)
                        cum = 0
                        for b, c in zip(fam.buckets, counts):
                            cum += c
                            line = _sample(
                                name + "_bucket",
                                key + (("le", _format_le(b)),),
                                cum,
                            )
                            if ex is not None and ex["value"] <= b:
                                line += " # {%s} %s %s" % (
                                    ",".join(
                                        f'{k}="{_escape_label_value(v)}"'
                                        for k, v in sorted(
                                            ex["labels"].items()
                                        )
                                    ),
                                    _format_value(ex["value"]),
                                    repr(float(ex["ts"])),
                                )
                                ex = None
                            lines.append(line)
                        lines.append(_sample(name + "_sum", key, total))
                        lines.append(_sample(name + "_count", key, count))
                    else:
                        lines.append(_sample(name, key, fam.values[key]))
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


#: Quantiles every histogram snapshot estimates (p50/p95/p99 — the
#: operator set; consumers wanting others call estimate_quantiles).
SNAPSHOT_QUANTILES = (0.5, 0.95, 0.99)


def estimate_quantiles(bounds, counts, qs=SNAPSHOT_QUANTILES):
    """Estimate quantiles from histogram buckets by linear interpolation.

    ``bounds`` are the inclusive upper bucket boundaries (ending +Inf),
    ``counts`` the NON-cumulative per-bucket counts. Within a bucket the
    distribution is assumed uniform (the standard Prometheus
    ``histogram_quantile`` model); a quantile landing in the +Inf bucket
    returns the last finite bound (the estimate is saturated, never
    invented). Returns ``{q: value | None}`` — None when the histogram
    is empty. One implementation, so ``/status``, ``--metrics-out``
    consumers, obs_report, and tests stop re-deriving it from raw
    buckets independently.
    """
    total = sum(counts)
    out: dict = {}
    finite = [b for b in bounds if not math.isinf(b)]
    top = finite[-1] if finite else 0.0
    for q in qs:
        if total == 0:
            out[q] = None
            continue
        target = q * total
        cum = 0
        value = top
        for i, (b, c) in enumerate(zip(bounds, counts)):
            if c == 0:
                cum += c
                continue
            if cum + c >= target:
                if math.isinf(b):
                    value = top
                else:
                    lo = 0.0 if i == 0 else (
                        bounds[i - 1]
                        if not math.isinf(bounds[i - 1]) else 0.0
                    )
                    value = lo + (b - lo) * (target - cum) / c
                break
            cum += c
        out[q] = value
    return out


def _quantile_key(q: float) -> str:
    """0.5 -> "p50", 0.99 -> "p99" (snapshot key spelling)."""
    s = f"{q * 100:g}".replace(".", "_")
    return f"p{s}"


def _sample(name: str, labels: Tuple[Tuple[str, str], ...],
            value: float) -> str:
    if labels:
        inner = ",".join(
            f'{k}="{_escape_label_value(v)}"' for k, v in labels
        )
        return f"{name}{{{inner}}} {_format_value(value)}"
    return f"{name} {_format_value(value)}"


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    return (registry or default_registry()).render_prometheus()


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every component records into by default."""
    return _DEFAULT
