"""Span / trace_span: phase-level wall-time tracing.

A ``Span`` measures one named phase (a solver level's forward expand, a
backward resolve, a checkpoint write, a serving batch). Ending a span
fans out to up to three sinks:

* the metrics registry — ``gamesman_span_seconds{span=...}`` histogram
  plus ``gamesman_span_payload_total{span=...,key=...}`` counters for
  every integer payload field (frontier/children/batch sizes), so phase
  time AND phase volume are queryable from ``/metrics``;
* the per-level JSONL stream — the span re-emits exactly the record the
  engine's hand-rolled ``logger.log`` calls used to write
  (``{"phase": name, **fields, "secs": dur}``), so bench.py and every
  existing JSONL consumer parse unchanged;
* the installed ``TraceEventSink`` — one Chrome trace-event "complete"
  event (``ph: "X"``) per span, nested spans stacking naturally per
  thread in chrome://tracing / Perfetto. ``--trace-events out.json``
  installs a sink for the CLI.

The clock is injectable (``clock=``) so span timing is testable against
a fake clock without sleeping.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional

from gamesmanmpi_tpu.obs import flightrec
from gamesmanmpi_tpu.obs.registry import MetricsRegistry, default_registry

#: Registry families spans record into.
SPAN_SECONDS = "gamesman_span_seconds"
SPAN_PAYLOAD = "gamesman_span_payload_total"

# Process-wide trace sink (None = tracing off). One writer installs it
# (the CLI, a test); every Span checks it at end() time, so spans cost
# one None check when tracing is off.
_SINK_LOCK = threading.Lock()
_SINK: Optional["TraceEventSink"] = None


def set_trace_sink(sink: Optional["TraceEventSink"]) -> Optional["TraceEventSink"]:
    """Install (or clear, with None) the process trace sink; returns the
    previous one so scopes can restore it."""
    global _SINK
    with _SINK_LOCK:
        prev = _SINK
        _SINK = sink
    return prev


def get_trace_sink() -> Optional["TraceEventSink"]:
    return _SINK


class TraceEventSink:
    """Collects Chrome trace-event JSON "complete" events, thread-safe.

    The output loads in chrome://tracing, Perfetto, and speedscope:
    ``{"traceEvents": [{"ph": "X", "name", "ts", "dur", "pid", "tid",
    "args"}, ...]}`` with ts/dur in microseconds.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._pid = os.getpid()

    def add_complete(self, name: str, t0: float, dur: float, tid: int,
                     args: Optional[dict] = None) -> None:
        """t0/dur in SECONDS on the span clock; stored as microseconds."""
        ev = {
            "ph": "X",
            "name": str(name),
            "ts": round(t0 * 1e6, 3),
            "dur": round(dur * 1e6, 3),
            "pid": self._pid,
            "tid": int(tid),
        }
        if args:
            # Trace args must be JSON-serializable; stringify anything
            # exotic (numpy scalars already went through int()/float()).
            ev["args"] = {
                k: (v if isinstance(v, (int, float, bool, str, type(None)))
                    else str(v))
                for k, v in args.items()
            }
        with self._lock:
            self._events.append(ev)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def span_names(self) -> set:
        with self._lock:
            return {e["name"] for e in self._events}

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "traceEvents": list(self._events),
                "displayTimeUnit": "ms",
            }

    def dump(self, path) -> None:
        tmp = str(path) + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.to_dict(), fh)
        os.replace(tmp, path)


class Span:
    """One timed phase. Construction starts the clock; ``end()`` stops it
    and fans out (idempotent — a with-block exit after an explicit end is
    a no-op). ``set()`` attaches payload fields; they ride into the JSONL
    record, the trace event's args, and (integers only) the payload
    counters."""

    __slots__ = ("name", "fields", "_clock", "_registry", "_logger",
                 "_t0", "_secs", "_log")

    def __init__(self, name: str, *, logger=None,
                 registry: Optional[MetricsRegistry] = None,
                 clock=None, log: bool = True, **fields):
        self.name = name
        self.fields = dict(fields)
        self._clock = clock or time.perf_counter
        self._registry = registry
        self._logger = logger
        self._log = log
        self._secs: Optional[float] = None
        self._t0 = self._clock()
        # Flight recorder (obs/flightrec.py): every span registers as
        # in-flight at construction so a post-mortem dump can name what
        # was running when the process died; end() converts it to a
        # ring event. One lock + dict op per span — span rate is
        # per-level/per-batch, never per-position. Guarded: the
        # recorder is an auxiliary surface and must never be able to
        # kill the solve it is recording.
        try:
            flightrec.default_recorder().span_begin(
                id(self), name, self.fields
            )
        except Exception:  # noqa: BLE001 - diagnostics only
            pass

    def set(self, **fields) -> "Span":
        self.fields.update(fields)
        return self

    @property
    def secs(self) -> Optional[float]:
        """Elapsed seconds; None until ended."""
        return self._secs

    def end(self, log: Optional[bool] = None, **fields) -> float:
        if self._secs is not None:  # idempotent
            return self._secs
        self._secs = self._clock() - self._t0
        if fields:
            self.fields.update(fields)
        if log is not None:
            self._log = log
        reg = self._registry or default_registry()
        reg.histogram(
            SPAN_SECONDS, "wall seconds per traced phase", span=self.name
        ).observe(self._secs)
        for k, v in self.fields.items():
            # Payload volume: integer fields are sizes/counts by
            # convention (bools are flags, not sizes; `level` is a
            # coordinate — summing it would be meaningless).
            if k != "level" and isinstance(v, int) and not isinstance(v, bool):
                reg.counter(
                    SPAN_PAYLOAD,
                    "summed integer payload fields of traced phases",
                    span=self.name, key=k,
                ).inc(v)
        try:
            flightrec.default_recorder().span_end(
                id(self), self.name, self._secs, self.fields
            )
        except Exception:  # noqa: BLE001 - diagnostics only
            pass
        sink = _SINK
        if sink is not None:
            sink.add_complete(
                self.name, self._t0, self._secs,
                threading.get_ident(), self.fields,
            )
        if self._logger is not None and self._log:
            self._logger.log(
                {"phase": self.name, **self.fields,
                 "secs": self._secs}
            )
        return self._secs


@contextlib.contextmanager
def trace_span(name: str, *, logger=None,
               registry: Optional[MetricsRegistry] = None,
               clock=None, log: bool = True, **fields):
    """Context-manager form: ``with trace_span("dedup", level=k):``.

    Yields the Span (call ``.set()`` to attach fields discovered inside
    the block); ends it on exit, exceptions included — a span around an
    aborted phase still records the time it consumed."""
    span = Span(name, logger=logger, registry=registry, clock=clock,
                log=log, **fields)
    try:
        yield span
    finally:
        span.end()


@contextlib.contextmanager
def trace_events_scope(path):
    """Install a fresh TraceEventSink for the duration of the block and
    dump it to ``path`` on exit (the ``--trace-events`` implementation;
    restores any previously installed sink)."""
    if not path:
        yield None
        return
    sink = TraceEventSink()
    prev = set_trace_sink(sink)
    try:
        yield sink
    finally:
        set_trace_sink(prev)
        sink.dump(path)
