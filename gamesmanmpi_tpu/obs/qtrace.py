"""Query-path distributed tracing: traceparent, spans, tail sampling.

The solve side traces *phases* (obs/tracing.py: one Span per level or
batch). The serving side needs the other axis: one trace per *request*,
attributing a single slow or shed query to the stage that ate its
latency — batcher queue wait, the canonicalize/searchsorted probe, a v2
block decode, a cold store read. This module is that read-side twin:

* ``parse_traceparent`` / ``format_traceparent`` / ``mint_trace_ids`` —
  the W3C ``traceparent`` wire form (``00-<32hex>-<16hex>-<2hex>``), so
  a client (``tools/load_gen.py``) can mint a trace id, send it with the
  query, and later join its own p99 outlier record to the server-side
  trace by id.
* ``QueryTrace`` — one request's trace: ids, route, wall start, and an
  append-only list of span dicts (name, start offset, duration, fields).
* ``activate``/``qspan`` — thread-local activation. The batcher
  coalesces many requests into one reader probe, so activation takes a
  *list* of traces and every span recorded inside the window appends to
  all of them (one decode, N attributions — exactly what coalescing
  means for latency accounting). When no trace is active — every solve
  code path — ``qspan`` yields immediately without reading a clock, so
  the hooks woven into db/reader.py and store/blockstore.py cost one
  tuple check.
* ``TraceRing`` — bounded per-worker ring with TAIL-based sampling:
  the keep decision runs at trace end, when the outcome is known. Every
  error/shed/tripped trace is kept, anything slower than
  ``GAMESMAN_TRACE_SLOW_MS`` is kept, and 1-in-``GAMESMAN_TRACE_HEAD_N``
  is kept regardless (the healthy-baseline sample). Kept traces also
  enter a small outbox the fleet worker drains into its heartbeat
  beats, which is how the supervisor aggregates fleet-wide traces
  without being able to HTTP-address an individual worker (all workers
  share one accept queue).

``GAMESMAN_TRACE=0`` turns the whole machinery into no-ops (the bench
A/B arm measures exactly this delta).

Span *names* recorded through ``qspan`` are part of the span-name
registry contract (GM405): literal first arguments, documented in
docs/OBSERVABILITY.md's "Span name registry" table.
"""

from __future__ import annotations

import contextlib
import os
import re
import threading
import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

from gamesmanmpi_tpu.obs.registry import MetricsRegistry, default_registry
from gamesmanmpi_tpu.utils.env import env_bool, env_float, env_int

#: Registry families the trace ring records into.
TRACE_KEPT = "gamesman_trace_kept_total"
TRACE_DROPPED = "gamesman_trace_dropped_total"

#: Trace outcomes that are always kept (tail sampling's whole point).
ALWAYS_KEEP = ("error", "shed", "tripped")

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def trace_enabled() -> bool:
    """Master switch: ``GAMESMAN_TRACE`` (default on)."""
    return env_bool("GAMESMAN_TRACE", True)


def _hex_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def mint_trace_ids() -> Tuple[str, str]:
    """Fresh (trace_id, span_id) pair for a root that got no context."""
    return _hex_id(16), _hex_id(8)


def format_traceparent(trace_id: str, span_id: str,
                       flags: str = "01") -> str:
    return f"00-{trace_id}-{span_id}-{flags}"


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """(trace_id, parent_span_id) from a ``traceparent`` header, or None
    when absent/malformed/all-zero (a malformed header must not kill the
    request — the server just mints a fresh root)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if not m:
        return None
    trace_id, span_id = m.group(2), m.group(3)
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return trace_id, span_id


class QueryTrace:
    """One request's trace. Spans are plain dicts so the ring snapshot,
    the heartbeat outbox, and ``GET /traces`` serialize them as-is."""

    __slots__ = ("trace_id", "parent_id", "root_id", "route", "start",
                 "spans", "status", "code", "keep_reason", "worker",
                 "_t0", "_secs", "_lock")

    def __init__(self, *, traceparent: Optional[str] = None,
                 route: str = "", worker=None, clock=None):
        parsed = parse_traceparent(traceparent)
        if parsed is not None:
            self.trace_id, self.parent_id = parsed
        else:
            self.trace_id, self.parent_id = _hex_id(16), None
        self.root_id = _hex_id(8)
        self.route = route
        self.worker = worker
        self.start = time.time()
        self._t0 = (clock or time.perf_counter)()
        self._secs: Optional[float] = None
        self.spans: List[dict] = []
        self.status = "ok"
        self.code = 200
        self.keep_reason: Optional[str] = None
        # Spans can land from the batcher worker thread while the
        # handler thread finishes the trace; appends are tiny.
        self._lock = threading.Lock()

    def add_span(self, name: str, start_offset: float, secs: float,
                 **fields) -> dict:
        """Record one span. ``start_offset``/``secs`` in seconds relative
        to the trace root; stored as milliseconds (the operator unit for
        request latency)."""
        span = {
            "name": str(name),
            "start_ms": round(start_offset * 1e3, 3),
            "dur_ms": round(secs * 1e3, 3),
        }
        for k, v in fields.items():
            span[k] = (v if isinstance(v, (int, float, bool, str,
                                           type(None))) else str(v))
        with self._lock:
            self.spans.append(span)
        return span

    def offset(self, clock=None) -> float:
        """Seconds since the trace root started (span start offsets)."""
        return (clock or time.perf_counter)() - self._t0

    def finish(self, *, status: str = "ok", code: int = 200,
               clock=None) -> float:
        """Stop the trace clock (idempotent); returns duration seconds."""
        if self._secs is None:
            self._secs = (clock or time.perf_counter)() - self._t0
        self.status = status
        self.code = int(code)
        return self._secs

    @property
    def duration_ms(self) -> Optional[float]:
        return None if self._secs is None else self._secs * 1e3

    def to_dict(self) -> dict:
        with self._lock:
            spans = list(self.spans)
        out = {
            "trace_id": self.trace_id,
            "span_id": self.root_id,
            "parent_id": self.parent_id,
            "route": self.route,
            "start": self.start,
            "status": self.status,
            "code": self.code,
            "dur_ms": (None if self._secs is None
                       else round(self._secs * 1e3, 3)),
            "spans": spans,
        }
        if self.worker is not None:
            out["worker"] = self.worker
        if self.keep_reason is not None:
            out["keep"] = self.keep_reason
        return out


# Thread-local active-trace set. A tuple (not a list): activation swaps
# the whole binding, readers never see a half-updated container.
_TLS = threading.local()


def active_traces() -> Tuple[QueryTrace, ...]:
    return getattr(_TLS, "traces", ())


@contextlib.contextmanager
def activate(traces: Sequence[QueryTrace]):
    """Bind ``traces`` as this thread's active set for the block. The
    batcher activates the whole coalesced batch around ``lookup_best``;
    the HTTP handler activates its single request trace."""
    prev = getattr(_TLS, "traces", ())
    _TLS.traces = tuple(t for t in traces if t is not None)
    try:
        yield
    finally:
        _TLS.traces = prev


@contextlib.contextmanager
def qspan(name: str, **fields):
    """Record one named span onto every active query trace.

    The no-trace fast path (every solve call site) is one attribute
    fetch and a tuple truth-test — no clock read, no allocation. Fields
    set on the yielded dict-like handle after the block starts are
    merged into the recorded span.
    """
    traces = getattr(_TLS, "traces", ())
    if not traces:
        yield None
        return
    t0 = time.perf_counter()
    extra: dict = {}
    try:
        yield extra
    finally:
        secs = time.perf_counter() - t0
        if extra:
            fields = {**fields, **extra}
        for tr in traces:
            tr.add_span(name, t0 - tr._t0, secs, **fields)


class TraceRing:
    """Bounded ring of finished traces with tail-based sampling.

    ``offer()`` is the single decision point: error/shed/tripped always
    kept, slow (>= ``slow_ms``) kept, then 1-in-``head_n`` head
    sampling. Kept traces also enter the outbox (bounded) the fleet
    worker drains into heartbeat beats. All state behind one lock —
    offer rate is per-request, never per-position.
    """

    def __init__(self, *, capacity: Optional[int] = None,
                 slow_ms: Optional[float] = None,
                 head_n: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.capacity = max(1, int(
            capacity if capacity is not None
            else env_int("GAMESMAN_TRACE_RING", 512)
        ))
        self.slow_ms = float(
            slow_ms if slow_ms is not None
            else env_float("GAMESMAN_TRACE_SLOW_MS", 100.0)
        )
        self.head_n = max(1, int(
            head_n if head_n is not None
            else env_int("GAMESMAN_TRACE_HEAD_N", 50)
        ))
        self.enabled = (trace_enabled() if enabled is None
                        else bool(enabled))
        self._registry = registry or default_registry()
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._outbox: deque = deque(maxlen=64)
        self._seen = 0
        self._kept = 0
        self._dropped = 0

    def decide(self, trace: QueryTrace) -> Optional[str]:
        """The sampling verdict alone (no mutation): keep reason or
        None. Split out so tests can hammer the policy directly."""
        if trace.status in ALWAYS_KEEP:
            return trace.status
        dur = trace.duration_ms
        if dur is not None and dur >= self.slow_ms:
            return "slow"
        return None

    def offer(self, trace: QueryTrace) -> Optional[str]:
        """Finished trace in; keep reason out (None = dropped)."""
        if not self.enabled:
            return None
        reason = self.decide(trace)
        with self._lock:
            self._seen += 1
            if reason is None and (self._seen % self.head_n) == 1 % self.head_n:
                reason = "head"
            if reason is None:
                self._dropped += 1
                self._registry.counter(
                    TRACE_DROPPED,
                    "finished query traces the tail sampler dropped",
                ).inc()
                return None
            trace.keep_reason = reason
            rec = trace.to_dict()
            self._ring.append(rec)
            self._outbox.append(rec)
            self._kept += 1
        self._registry.counter(
            TRACE_KEPT, "query traces kept by the tail sampler",
            reason=reason,
        ).inc()
        return reason

    def drain_outbox(self, n: int = 8) -> List[dict]:
        """Up to ``n`` newly kept traces for the heartbeat beat; what's
        drained never re-ships."""
        out: List[dict] = []
        with self._lock:
            while self._outbox and len(out) < int(n):
                out.append(self._outbox.popleft())
        return out

    def snapshot(self, limit: Optional[int] = None) -> dict:
        """The ``GET /traces`` payload: newest-last kept traces plus the
        sampler's own accounting."""
        with self._lock:
            traces = list(self._ring)
            seen, kept, dropped = self._seen, self._kept, self._dropped
        if limit is not None and limit >= 0:
            traces = traces[-int(limit):]
        return {
            "kind": "qtrace_ring",
            "seen": seen,
            "kept": kept,
            "dropped": dropped,
            "capacity": self.capacity,
            "slow_ms": self.slow_ms,
            "head_n": self.head_n,
            "enabled": self.enabled,
            "traces": traces,
        }

    def find(self, trace_id: str) -> Optional[dict]:
        """Newest kept trace with this id (tests and debugging joins)."""
        with self._lock:
            for rec in reversed(self._ring):
                if rec.get("trace_id") == trace_id:
                    return rec
        return None
