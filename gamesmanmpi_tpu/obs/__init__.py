"""obs: the unified observability layer (registry + spans + heartbeat).

The reference GamesmanMPI had rank-0 stdout prints; this rebuild's
north-star metric is positions-solved/sec/chip, which demands knowing
where level time actually goes (sort vs gather vs comms — the lesson of
the Pentago strong solve, arXiv:1404.0743, and the consumer-grade 7x6
Connect-Four solve, arXiv:2507.05267). Three pieces, one subsystem:

* ``MetricsRegistry`` (registry.py): process-wide counters / gauges /
  bucketed histograms, thread-safe, snapshot-able to a dict and
  renderable as Prometheus text exposition v0.0.4. ``default_registry()``
  is the process singleton every component records into unless handed an
  explicit registry (tests isolate with fresh instances).
* ``Span`` / ``trace_span`` (tracing.py): wall-time spans around solver
  phases (forward expand, dedup, backward resolve, checkpoint, db
  export) and server request/batch stages. Spans land in the registry
  (``gamesman_span_seconds``), optionally re-emit the existing per-level
  JSONL records (bench.py parsing unchanged), and stream Chrome
  trace-event JSON through an installed ``TraceEventSink``
  (``--trace-events out.json``) alongside the ``maybe_profile`` JAX trace.
* ``Heartbeat`` (heartbeat.py): a daemon thread that periodically logs
  level progress, RSS, and device memory stats so a multi-hour solve is
  diagnosable mid-flight.
* ``StatusServer`` / ``SolveStatusTracker`` (status.py, ISSUE 15): a
  read-only ``/status`` + ``/metrics`` HTTP endpoint served from the
  solver process (``GAMESMAN_STATUS_PORT``) with a level-schedule
  progress model + ETA, fleet-merged per-rank view on rank 0, and the
  campaign proxy one stable port across restarts.
* ``FlightRecorder`` (flightrec.py, ISSUE 15): an always-on bounded
  ring of recent spans/levels/retries/faults/store events dumped as
  ``flightrec_<rank>.json`` on every abnormal exit — the post-mortem
  that used to need a rerun under instrumentation.
* ``QueryTrace`` / ``qspan`` / ``TraceRing`` (qtrace.py, ISSUE 17):
  per-request distributed tracing for the serving fleet — W3C
  ``traceparent`` at ingress, queue/probe/decode/store spans, a
  tail-sampled per-worker ring behind ``GET /traces``.
* ``SloEngine`` (slo.py, ISSUE 17): declared availability + p99-latency
  objectives per route with multi-window burn rates; fast-burn folds
  into ``/healthz`` as ``degraded``.

docs/OBSERVABILITY.md is the operator guide.
"""

from gamesmanmpi_tpu.obs.registry import (
    MetricsRegistry,
    default_registry,
    render_prometheus,
)
from gamesmanmpi_tpu.obs.tracing import (
    Span,
    TraceEventSink,
    get_trace_sink,
    set_trace_sink,
    trace_span,
)
from gamesmanmpi_tpu.obs.heartbeat import Heartbeat
from gamesmanmpi_tpu.obs.flightrec import FlightRecorder, default_recorder
from gamesmanmpi_tpu.obs.qtrace import (
    QueryTrace,
    TraceRing,
    activate,
    active_traces,
    format_traceparent,
    mint_trace_ids,
    parse_traceparent,
    qspan,
    trace_enabled,
)
from gamesmanmpi_tpu.obs.slo import SloEngine
from gamesmanmpi_tpu.obs.status import (
    SolveStatusTracker,
    StatusServer,
    maybe_status_server,
)

__all__ = [
    "MetricsRegistry",
    "default_registry",
    "render_prometheus",
    "Span",
    "TraceEventSink",
    "get_trace_sink",
    "set_trace_sink",
    "trace_span",
    "Heartbeat",
    "FlightRecorder",
    "default_recorder",
    "SolveStatusTracker",
    "StatusServer",
    "maybe_status_server",
    "QueryTrace",
    "TraceRing",
    "activate",
    "active_traces",
    "format_traceparent",
    "mint_trace_ids",
    "parse_traceparent",
    "qspan",
    "trace_enabled",
    "SloEngine",
]
