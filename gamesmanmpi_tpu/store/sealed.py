"""The store's single sealed-read path.

Every on-disk payload in this repo is *sealed*: its integrity record
(crc32 in a checkpoint manifest, per-block crc32 + sha256 in a DB
manifest) is written atomically AFTER the payload lands. Before this
module existed, three near-duplicate consumers re-implemented the
read half of that contract — ``LevelCheckpointer`` (crc-check →
quarantine → degrade), the sharded edge-shard loader (torn file →
fall back to the lookup backward), and ``db/reader._BlockedLevel``
(pread + per-block crc → reader fault). They now all read through
here:

* :data:`TORN_SEAL_ERRORS` — the one tuple of exception shapes a
  torn/truncated/deleted/bit-rotted sealed read can raise. Callers
  that degrade (quarantine + recompute, lookup fallback) catch exactly
  this; ``utils/checkpoint.TORN_NPZ_ERRORS`` is the same object.
* :func:`verify_crc` — streaming crc32 check against the sealed value,
  raising :class:`CorruptSealError` (a ``ValueError``, so it rides the
  torn tuple). Quarantine is the CALLER's move, on the caller's
  thread: this function is pure so it is safe to run on a prefetch
  thread — corruption discovered in the background re-raises on the
  consuming thread and degrades there, never mutates a manifest from
  a worker.
* :func:`loadz` / :class:`BlockedNpzView` — the one np.load door for
  checkpoint/spill npz files, transparent to ``blocks`` framing.
* :class:`SealedBlockStream` — the v2 DB probe-side handle: resident
  block index over ``os.pread`` + crc-verified block decode.
* :func:`open_npy_mmap` — the v1 DB level mmap door.

Direct ``np.load`` / ``os.pread`` / ``open(..., "rb")`` of payload
files anywhere outside ``store/`` is a lint finding (GM803 store-io).
"""

from __future__ import annotations

import json
import os
import pathlib
import zipfile
import zlib

import numpy as np

from gamesmanmpi_tpu.compress import (
    BlockCorruptError,
    decode_array,
    decode_block,
    index_offsets,
    validate_index,
)


class CorruptSealError(ValueError):
    """A sealed file failed its recorded crc32 — silent bit-rot or an
    overwrite the torn-zip errors cannot see. Subclasses ValueError so
    every TORN_SEAL_ERRORS degrade path treats it as one more torn-file
    shape. (``utils/checkpoint.CorruptCheckpointError`` is this class.)
    """


#: What a torn/truncated/deleted sealed read can raise (ADVICE r5):
#: missing file, a zip whose central directory never landed, a short
#: read surfacing as a bare OSError, a zip that lost a member (KeyError
#: on z["name"]), or overwritten-with-garbage content (np.load raises
#: ValueError when the bytes are neither zip nor npy; CorruptSealError
#: and compress' BlockCorruptError are ValueErrors too). Loaders that
#: degrade to an intact prefix catch exactly this tuple.
TORN_SEAL_ERRORS = (
    FileNotFoundError, zipfile.BadZipFile, OSError, KeyError, ValueError
)


def file_crc32(path, chunk: int = 1 << 20) -> int:
    """Streaming crc32 of a file (zlib polynomial, chunked reads — disk
    speed, constant memory, so sealing a multi-GB shard stays cheap)."""
    crc = 0
    with open(path, "rb") as fh:
        while True:
            block = fh.read(chunk)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def verify_crc(path, want) -> None:
    """Check one sealed file against its recorded crc32.

    ``want`` is the sealed value (int) or None (nothing recorded —
    pre-integrity files keep loading). Raises CorruptSealError on
    mismatch. Pure: no quarantine, no manifest writes — safe on any
    thread (the prefetch pool runs it; the error re-raises at the
    consuming read and the caller quarantines there)."""
    if want is None:
        return
    path = pathlib.Path(path)
    if not path.exists():
        return
    got = file_crc32(path)
    if got != int(want):
        raise CorruptSealError(
            f"{path.name}: crc32 {got:#010x} != sealed {int(want):#010x}"
            " — quarantine and recompute"
        )


#: npz member name of the block-framing metadata (GAMESMAN_CKPT_COMPRESS=
#: blocks): JSON bytes mapping each framed member to its block index.
#: Double-underscored so it can never collide with a real array name
#: (states/cells/eidx/slot/level_NNNN...).
BLOCKS_META_MEMBER = "__blocks__"


class BlockedNpzView:
    """Dict-like view over a block-framed npz (the ``blocks`` flavor of
    checkpoint._savez): same ``files`` / ``[]`` / context-manager
    surface as np.load's NpzFile, decoding framed members on access.
    Corrupt blocks raise BlockCorruptError (ValueError) from ``[]`` —
    exactly where a torn plain npz raises — so every TORN_SEAL_ERRORS
    consumer degrades identically for both storage flavors."""

    def __init__(self, z, meta: dict):
        self._z = z
        self._meta = meta

    @property
    def files(self):
        return [n for n in self._z.files if n != BLOCKS_META_MEMBER]

    def __getitem__(self, name):
        raw = self._z[name]
        index = self._meta.get(name)
        if index is None:
            return raw
        return decode_array(index, raw.tobytes())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._z.close()
        return False

    def close(self):
        self._z.close()


def loadz(path):
    """np.load for checkpoint/spill npz files, transparent to block
    framing: plain npz returns as-is; a ``__blocks__`` member returns
    the decoding view. The single load door for every checkpoint/spill
    consumer — which is what makes the compressed format invisible to
    the resume/quarantine machinery above it."""
    z = np.load(path)
    if BLOCKS_META_MEMBER not in z.files:
        return z
    try:
        meta = json.loads(bytes(z[BLOCKS_META_MEMBER]))
    except (ValueError, KeyError):
        z.close()
        raise  # ValueError: a TORN_SEAL_ERRORS member — degrade as torn
    return BlockedNpzView(z, meta)


def read_npz_members(path, names=None, crc=None):
    """The sealed-read primitive for npz payloads: crc-verify, load,
    materialize. -> tuple of arrays (``names`` given) or {name: array}.

    Materializing (np.asarray) here — not at the consumer — is what
    makes prefetch useful: a hinted file is *decoded* on the pool
    thread, so the solve thread's later read is a pure cache hit.
    Raises a TORN_SEAL_ERRORS member on any corruption; never mutates
    anything (see verify_crc)."""
    verify_crc(path, crc)
    with loadz(path) as z:
        if names is None:
            return {n: np.asarray(z[n]) for n in z.files}
        return tuple(np.asarray(z[n]) for n in names)


def open_npy_mmap(path):
    """Memory-map a sealed plain .npy payload (v1 DB levels): the mmap
    IS the cache for this format — a binary search touches O(log n)
    pages — so it bypasses the byte-budget tier on purpose."""
    return np.load(path, mmap_mode="r")


class SealedBlockStream:
    """Probe-side handle on one sealed pair of framed block streams
    (a v2 DB level's keys+cells): resident block router (first_keys +
    derived offsets) over fd reads with os.pread, so concurrent
    flush/breaker/caller threads — and forked fleet workers sharing the
    parent's fds — never contend on a file position."""

    def __init__(self, directory: pathlib.Path, level: int, rec: dict):
        self.level = level
        self.count = int(rec["count"])
        self.keys_index = rec["keys_blocks"]
        self.cells_index = rec["cells_blocks"]
        self.first_keys = np.asarray(
            rec.get("first_keys", []), dtype=np.uint64
        )
        self.keys_fd = self.cells_fd = -1
        try:
            self.keys_fd = os.open(directory / rec["keys"], os.O_RDONLY)
            self.cells_fd = os.open(directory / rec["cells"], os.O_RDONLY)
            # Validate the index against the real stream sizes at open:
            # a truncated block file fails HERE (DbFormatError at reader
            # construction / first touch), not as an out-of-range pread
            # mid-probe.
            validate_index(
                self.keys_index,
                stream_bytes=os.fstat(self.keys_fd).st_size,
            )
            validate_index(
                self.cells_index,
                stream_bytes=os.fstat(self.cells_fd).st_size,
            )
            if len(self.first_keys) != len(self.keys_index["lengths"]):
                raise BlockCorruptError(
                    f"level {level}: {len(self.first_keys)} first_keys "
                    f"for {len(self.keys_index['lengths'])} blocks"
                )
            # Cache identity: (dev, ino) of the keys stream. Inode-based
            # so entries survive nothing they shouldn't — an overwrite
            # swap (DbWriter --overwrite) installs NEW files with new
            # inodes, so a reader opened on the new directory can never
            # hit the old directory's decoded blocks in a shared cache.
            st = os.fstat(self.keys_fd)
            self.ident = (int(st.st_dev), int(st.st_ino))
        except BaseException:
            self.close()
            raise
        self.keys_offsets = index_offsets(self.keys_index)
        self.cells_offsets = index_offsets(self.cells_index)

    @property
    def num_blocks(self) -> int:
        return len(self.first_keys)

    def read_block(self, b: int):
        """Decode block b -> (keys, cells) arrays (crc-verified)."""
        kb = os.pread(
            self.keys_fd,
            int(self.keys_offsets[b + 1] - self.keys_offsets[b]),
            int(self.keys_offsets[b]),
        )
        cb = os.pread(
            self.cells_fd,
            int(self.cells_offsets[b + 1] - self.cells_offsets[b]),
            int(self.cells_offsets[b]),
        )
        return (
            decode_block(self.keys_index, b, kb),
            decode_block(self.cells_index, b, cb),
        )

    def close(self) -> None:
        for fd in (self.keys_fd, self.cells_fd):
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self.keys_fd = self.cells_fd = -1
