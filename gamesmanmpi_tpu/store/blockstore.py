"""BlockStore: one async engine under every tiered I/O path.

Four hand-rolled synchronous paths used to move every on-disk byte
(checkpoint npz framing, DB block streams, sharded edge/frontier spill
files, the reader's pread+LRU), and every spill load blocked the solve
thread — compression was a storage win but not a speed win. This module
is the unification ROADMAP item 2 calls for: crc-sealed block
read/write with a background prefetch + write-behind pool, pluggable
codecs via the existing keydelta/cellpack registry (the sealed readers
decode through ``compress/``), and one byte-budget host-RAM cache
(:class:`~gamesmanmpi_tpu.store.cache.TieredCache`) in front of the
disk tier.

Read side
=========

``read(key, loader)`` is the one door: cache hit → return; an in-flight
prefetch for the same key → wait for it (the wait, not the whole load,
is the solve thread's I/O cost); otherwise load synchronously. ``hint``
schedules the loader on the prefetch pool — the solver's level schedule
hints level N-1's edge/checkpoint shards while level N computes, so the
next level's loads are decoded before the solve thread asks
(overlapping level N's compute with level N-1's decode/disk I/O is the
design "Compressed Game Solving" and the 7x6 Connect-Four solve both
show out-of-core retrograde lives or dies on). A hinted-but-evicted
key degrades to a synchronous read — never a wrong answer, never a
wait on a lost future.

Error contract: a loader exception on the pool is *stored* and
re-raised at the consuming ``read`` on the caller's thread — a torn or
bit-rotted block surfaced by a background prefetch still raises into
``TORN_NPZ_ERRORS`` on the solve thread, where quarantine-and-degrade
lives. Loaders must therefore be pure (see store/sealed.py).

Write side
==========

``write(fn, path=...)`` enqueues a payload write (the DEFLATE+fsync of
one ``_savez``) on a single ordered worker and returns a
:class:`WriteTicket`; ``drain()`` barriers on the queue and re-raises
the first failure. Ordering with seals (the GM8xx discipline): payload
writes go through the queue, manifest seals stay on the caller's
thread and call ``drain()`` first — so write-behind completes before
anything is sealed, and a death mid-queue leaves unsealed strays the
resume machinery already ignores (chaos-verified at the
``store.writebehind`` fault point). The worker is ONE thread on
purpose: FIFO order is the correctness argument, and the overlap win
is solve-thread-vs-writer, not writer-vs-writer.

Accounting (the A/B observable): ``io_wait_secs`` accumulates every
second the *calling* thread spent blocked on store I/O — synchronous
loads, waits on in-flight prefetches, drains, and (write-behind off)
inline writes. A sync-vs-prefetch A/B of the same solve moves the same
bytes; only io_wait shrinks (BENCH_store_r11.json gates on exactly
that). ``prefetch_hit_rate`` and ``writebehind_queue_depth`` ride the
same stats dict into solver stats, JSONL records, and the
``gamesman_store_*`` registry series (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import collections
import os
import threading
import time

from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.obs import flightrec
from gamesmanmpi_tpu.obs.qtrace import qspan
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.store.cache import TieredCache
from gamesmanmpi_tpu.utils.env import env_bool, env_int

#: Host-RAM tier default: 256 MB holds the decoded working set of a
#: spill-heavy mid-size solve (a few hundred 64Ki-position block pairs)
#: while staying invisible next to the frontier arrays themselves.
_DEFAULT_CACHE_MB = 256
_DEFAULT_PREFETCH_THREADS = 2


class WriteTicket:
    """One enqueued write-behind payload write.

    ``result()`` blocks until the write lands and returns the write
    fn's return value (the checkpoint savers return (raw, stored)
    bytes), re-raising the write's failure. Resolved synchronously when
    write-behind is off."""

    __slots__ = ("path", "consumed", "_event", "_value", "_error")

    def __init__(self, path=None):
        self.path = path
        #: True once result() delivered the outcome to a caller — a
        #: failure somebody already handled must not be re-raised at a
        #: later, unrelated drain() (see BlockStore.drain).
        self.consumed = False
        self._event = threading.Event()
        self._value = None
        self._error = None

    def _resolve(self, value=None, error=None) -> None:
        self._value = value
        self._error = error
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError(f"write-behind of {self.path} still queued")
        self.consumed = True
        if self._error is not None:
            raise self._error
        return self._value


class _Inflight:
    """One key's in-progress background load."""

    __slots__ = ("event", "value", "error")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error = None


def file_key(path):
    """Cache key for a sealed FILE payload: (path, mtime_ns, size).

    Stat-qualified so a rewritten/truncated/quarantined file can never
    serve stale cached bytes: the key a reader computes after the
    change differs from the key the old content was cached under, and
    the read degrades to a fresh sealed load. Returns None (bypass the
    cache, load synchronously) when the file cannot be stat'ed — the
    loader then raises the honest FileNotFoundError."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (str(path), st.st_mtime_ns, st.st_size)


class BlockStore:
    """Async block-store engine: tiered cache + prefetch + write-behind."""

    def __init__(self, *, cache: TieredCache | None = None,
                 prefetch_threads: int = _DEFAULT_PREFETCH_THREADS,
                 writebehind: bool = True, registry=None, labels=None):
        """labels: metric labels for THIS store's gamesman_store_*
        series. The process default store emits unlabeled; a private
        store (DbReader's legacy GAMESMAN_DB_CACHE_MB budget) passes
        ``db=<name>`` so its io_wait/prefetch counts never fold into
        the shared store's series (the same conflation class PR 9
        fixed for gamesman_db_cache_*)."""
        reg = registry if registry is not None else default_registry()
        lbl = dict(labels or {})
        self.cache = cache if cache is not None else TieredCache(
            _DEFAULT_CACHE_MB << 20, registry=reg
        )
        self.prefetch_threads = max(0, int(prefetch_threads))
        self.writebehind = bool(writebehind)
        self._lock = threading.Lock()
        self._inflight: dict = {}  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        # Counters (plain numbers under the one lock; snapshotted by
        # stats() — same pattern as the serving batcher's).
        self._io_wait_secs = 0.0  # guarded-by: _lock
        self._prefetch_hits = 0  # guarded-by: _lock
        self._prefetch_misses = 0  # guarded-by: _lock
        self._prefetch_issued = 0  # guarded-by: _lock
        self._reads = 0  # guarded-by: _lock
        # Prefetch pool: lazy daemon threads over one work deque.
        self._pf_cond = threading.Condition(self._lock)
        self._pf_queue: collections.deque = collections.deque()
        self._pf_started = 0  # guarded-by: _lock
        # Write-behind: ONE ordered daemon worker (see module doc).
        self._wb_cond = threading.Condition(self._lock)
        self._wb_queue: collections.deque = collections.deque()
        self._wb_busy = False  # guarded-by: _lock
        self._wb_failed = None  # guarded-by: _lock (first failed ticket)
        self._wb_thread = None
        self._wb_writes = 0  # guarded-by: _lock
        self._wb_depth_peak = 0  # guarded-by: _lock
        self._m_io_wait = reg.counter(
            "gamesman_store_io_wait_seconds_total",
            "seconds calling threads spent blocked on store I/O "
            "(sync loads, prefetch waits, drains, inline writes)",
            **lbl,
        )
        self._m_pf_hits = reg.counter(
            "gamesman_store_prefetch_hits_total",
            "store reads satisfied by the cache or an in-flight prefetch",
            **lbl,
        )
        self._m_pf_misses = reg.counter(
            "gamesman_store_prefetch_misses_total",
            "store reads that fell back to a synchronous sealed load",
            **lbl,
        )
        self._m_wb_depth = reg.gauge(
            "gamesman_store_writebehind_queue_depth",
            "payload writes parked behind the write-behind worker now",
            **lbl,
        )
        self._m_wb_writes = reg.counter(
            "gamesman_store_writebehind_writes_total",
            "payload writes executed by the write-behind worker",
            **lbl,
        )

    @classmethod
    def from_env(cls, registry=None) -> "BlockStore":
        reg = registry if registry is not None else default_registry()
        return cls(
            cache=TieredCache(
                max(1, env_int("GAMESMAN_STORE_CACHE_MB",
                               _DEFAULT_CACHE_MB)) << 20,
                registry=reg,
            ),
            prefetch_threads=env_int(
                "GAMESMAN_STORE_PREFETCH_THREADS",
                _DEFAULT_PREFETCH_THREADS,
            ),
            writebehind=env_bool("GAMESMAN_STORE_WRITEBEHIND", True),
            registry=reg,
        )

    # -------------------------------------------------------------- reads

    def read(self, key, loader, nbytes=None):
        """The one read door; see read_ex."""
        return self.read_ex(key, loader, nbytes=nbytes)[0]

    def read_ex(self, key, loader, nbytes=None):
        """-> (value, hit). Cache hit / in-flight wait count as hits
        (the solve thread did not run the load itself); a synchronous
        fallback counts as a miss. ``key=None`` bypasses the cache
        entirely (unstat-able file — see file_key).

        ``nbytes`` sizes the cache entry; None derives it from the
        value's ``.nbytes`` fields (arrays or tuples/dicts of arrays).

        When a query trace is active (the serving path, obs/qtrace.py)
        the read records a ``store_read`` span carrying which path
        answered — ``hit`` (cache), ``wait`` (in-flight prefetch), or
        ``sync`` (the loader ran on this thread); the solve path pays
        one no-op tuple check.
        """
        with qspan("store_read") as sp:
            value, hit = self._read_ex_traced(key, loader, nbytes, sp)
        return value, hit

    def _read_ex_traced(self, key, loader, nbytes, sp):
        entry = None
        if key is not None:
            with self._lock:
                self._reads += 1
            # Cache lookup outside the store lock: the cache has its own
            # lock, and nested unrelated locks are how deadlocks start.
            value = self.cache.get(key)
            if value is not None:
                with self._lock:
                    self._prefetch_hits += 1
                self._m_pf_hits.inc()
                if sp is not None:
                    sp["path"] = "hit"
                return value, True
            with self._lock:
                entry = self._inflight.get(key)
            if entry is not None:
                t0 = time.perf_counter()
                entry.event.wait()
                self._note_wait(time.perf_counter() - t0)
                if entry.error is not None:
                    # Background corruption re-raises HERE, on the
                    # consuming thread — quarantine/degrade run where
                    # they always did. The entry was already dropped by
                    # the worker, so a retry reloads fresh.
                    with self._lock:
                        self._prefetch_misses += 1
                    self._m_pf_misses.inc()
                    raise entry.error
                with self._lock:
                    self._prefetch_hits += 1
                self._m_pf_hits.inc()
                if sp is not None:
                    sp["path"] = "wait"
                return entry.value, True
        with self._lock:
            if key is None:
                self._reads += 1
            self._prefetch_misses += 1
        self._m_pf_misses.inc()
        if sp is not None:
            sp["path"] = "sync"
        t0 = time.perf_counter()
        try:
            value = loader()
        finally:
            self._note_wait(time.perf_counter() - t0)
        if key is not None:
            self.cache.put(key, value, self._sizeof(value, nbytes))
        return value, False

    def hint(self, key, loader, nbytes=None) -> None:
        """Schedule a background load of ``key`` (the readahead half of
        the level schedule's batched hints). No-op when the key is
        None, already cached, already in flight, or the pool is
        disabled (GAMESMAN_STORE_PREFETCH_THREADS=0 — the sync A/B
        arm)."""
        if key is None or self.prefetch_threads <= 0:
            return
        if self.cache.contains(key):
            return  # peek, not get: a hint must not skew hit accounting
        spawn = 0
        with self._lock:
            if self._closed or key in self._inflight:
                return
            self._inflight[key] = _Inflight()
            self._prefetch_issued += 1
            self._pf_queue.append((key, loader, nbytes))
            # Grow the pool lazily up to prefetch_threads (an idle
            # spare thread is cheaper than per-thread busy tracking).
            # The Thread construction/start happens OUTSIDE the lock.
            if self._pf_started < self.prefetch_threads:
                self._pf_started += 1
                spawn = self._pf_started
            self._pf_cond.notify()
        if spawn:
            threading.Thread(
                target=self._prefetch_loop,
                name=f"gamesman-store-prefetch-{spawn - 1}",
                daemon=True,
            ).start()

    def _prefetch_loop(self) -> None:
        while True:
            with self._pf_cond:
                while not self._pf_queue and not self._closed:
                    self._pf_cond.wait()
                if self._closed and not self._pf_queue:
                    return
                key, loader, nbytes = self._pf_queue.popleft()
                entry = self._inflight.get(key)
            if entry is None:  # pragma: no cover - defensive
                continue
            try:
                value = loader()
            except BaseException as e:  # noqa: BLE001 - re-raised at read
                # Store events belong in the flight recorder: a torn
                # block surfacing minutes later reads back to this.
                flightrec.record("store_read_error", key=str(key)[:120],
                                 error=str(e)[:120])
                entry.error = e
                with self._lock:
                    self._inflight.pop(key, None)
                entry.event.set()
                continue
            entry.value = value
            self.cache.put(key, value, self._sizeof(value, nbytes))
            with self._lock:
                self._inflight.pop(key, None)
            entry.event.set()

    @staticmethod
    def _sizeof(value, nbytes) -> int:
        if nbytes is not None:
            return int(nbytes)
        if hasattr(value, "nbytes"):
            return int(value.nbytes)
        if isinstance(value, dict):
            vals = value.values()
        elif isinstance(value, (tuple, list)):
            vals = value
        else:
            return 0
        return int(sum(getattr(v, "nbytes", 0) for v in vals))

    # ------------------------------------------------------------- writes

    def write(self, fn, path=None) -> WriteTicket:
        """Enqueue one payload write (write-behind on) or execute it
        inline (off / closed), returning its ticket. ``path`` names the
        target file for diagnostics and the ``store.writebehind`` fault
        point's torn-write target."""
        ticket = WriteTicket(path)
        enqueued = False
        if self.writebehind:
            with self._wb_cond:
                if not self._closed:
                    self._wb_queue.append((ticket, fn))
                    depth = len(self._wb_queue) + (
                        1 if self._wb_busy else 0
                    )
                    self._wb_depth_peak = max(self._wb_depth_peak, depth)
                    if self._wb_thread is None:
                        self._wb_thread = threading.Thread(
                            target=self._writebehind_loop,
                            name="gamesman-store-writebehind", daemon=True,
                        )
                        self._wb_thread.start()
                    self._wb_cond.notify()
                    enqueued = True
            if enqueued:
                self._m_wb_depth.set(depth)
                return ticket
        self._run_write(ticket, fn)
        return ticket

    def _run_write(self, ticket: WriteTicket, fn) -> None:
        """Execute one write on the CALLING thread (sync mode): the
        solve thread is blocked for the duration, so it counts as
        io_wait — the denominator the write-behind A/B shrinks. The
        failure raises directly (the caller IS the writer here); it is
        not recorded for drain(), which would double-surface it."""
        t0 = time.perf_counter()
        try:
            value = fn()
            # Inside the try: an armed transient/fatal at the fault
            # point must behave exactly like a write failure (resolve
            # the ticket, surface to the caller), never leave an
            # unresolved ticket behind. kill/torn kinds exit outright.
            faults.fire("store.writebehind", path=ticket.path)
        except BaseException as e:  # noqa: BLE001 - also surfaced via ticket
            ticket._resolve(error=e)
            self._note_wait(time.perf_counter() - t0)
            with self._lock:
                self._wb_writes += 1
            raise
        self._note_wait(time.perf_counter() - t0)
        with self._lock:
            self._wb_writes += 1
        ticket._resolve(value)

    def _writebehind_loop(self) -> None:
        while True:
            with self._wb_cond:
                self._wb_busy = False
                self._wb_cond.notify_all()  # wake drain()ers
                while not self._wb_queue and not self._closed:
                    self._wb_cond.wait()
                if not self._wb_queue:
                    return  # closed and drained
                ticket, fn = self._wb_queue.popleft()
                self._wb_busy = True
                depth = len(self._wb_queue) + 1
            self._m_wb_depth.set(depth)
            try:
                value = fn()
                # Fire AFTER the payload lands and BEFORE any seal can
                # run (seals drain first): a kill here is the death-
                # between-payload-and-seal shape — resume must see an
                # unsealed stray and recompute, never a sealed-but-
                # missing level. INSIDE the try: an injected transient/
                # fatal must resolve the ticket and surface at the
                # seal, not kill this daemon and wedge every drain.
                faults.fire("store.writebehind", path=ticket.path)
            except BaseException as e:  # noqa: BLE001 - surfaced at drain
                flightrec.record(
                    "store_write_error",
                    path=str(ticket.path)[:160], error=str(e)[:120],
                )
                with self._lock:
                    self._wb_writes += 1
                    if self._wb_failed is None:
                        self._wb_failed = ticket
                ticket._resolve(error=e)
                self._m_wb_depth.set(len(self._wb_queue))
                continue
            with self._lock:
                self._wb_writes += 1
            ticket._resolve(value)
            # The honest remaining depth, INCLUDING the idle case: a
            # gauge stuck at 1 after the last write reads as a wedged
            # worker on an operator dashboard.
            self._m_wb_depth.set(len(self._wb_queue))

    def drain(self) -> None:
        """Barrier on the write-behind queue; re-raise the first queued
        write's failure — unless its ticket was already consumed by
        result() (the seal that owned it surfaced the error; re-raising
        at a later, unrelated drain would misattribute an old failure
        to a healthy quarantine/seal cycle). Cleared either way: one
        failure surfaces exactly once. Called by every seal before it
        writes a manifest: payload-before-seal is the whole ordering
        contract."""
        t0 = time.perf_counter()
        with self._wb_cond:
            while self._wb_queue or self._wb_busy:
                self._wb_cond.wait()
            failed, self._wb_failed = self._wb_failed, None
        waited = time.perf_counter() - t0
        if waited > 1e-6:
            self._note_wait(waited)
        if failed is not None and not failed.consumed:
            failed.consumed = True
            raise failed._error

    # -------------------------------------------------------------- misc

    def _note_wait(self, secs: float) -> None:
        with self._lock:
            self._io_wait_secs += secs
        self._m_io_wait.inc(max(0.0, secs))

    def stats(self) -> dict:
        """Point-in-time counters (the solver snapshots these at solve
        start and reports per-solve deltas in its stats)."""
        with self._lock:
            reads = self._prefetch_hits + self._prefetch_misses
            return {
                "io_wait_secs": self._io_wait_secs,
                "reads": self._reads,
                "prefetch_hits": self._prefetch_hits,
                "prefetch_misses": self._prefetch_misses,
                "prefetch_issued": self._prefetch_issued,
                "prefetch_hit_rate": (
                    self._prefetch_hits / reads if reads else 0.0
                ),
                "writebehind_writes": self._wb_writes,
                "writebehind_queue_depth": (
                    len(self._wb_queue) + (1 if self._wb_busy else 0)
                ),
                "writebehind_queue_depth_peak": self._wb_depth_peak,
            }

    def close(self) -> None:
        """Drain writes, stop accepting background work, release the
        cache. Late ``write`` calls degrade to inline execution and
        late ``hint`` calls no-op, so a consumer holding a stale store
        (after default_store() rebuilt on an env change) stays correct,
        just synchronous."""
        self.drain()
        with self._wb_cond:
            self._closed = True
            self._wb_cond.notify_all()
            self._pf_cond.notify_all()
        self.cache.clear()


#: Process-wide store singleton, keyed on the env knobs it was built
#: from: a test (or operator) changing GAMESMAN_STORE_* gets a fresh
#: store on the next default_store() call instead of a stale config.
_DEFAULT_LOCK = threading.Lock()
_DEFAULT: tuple | None = None


def default_store() -> BlockStore:
    """The shared store every consumer defaults to — one byte budget,
    one prefetch pool, one write-behind queue per process (checkpoint
    writers, spill readers, and DB serving all meet here, which is the
    unification that replaces the per-reader private LRUs)."""
    global _DEFAULT
    knobs = (
        env_int("GAMESMAN_STORE_CACHE_MB", _DEFAULT_CACHE_MB),
        env_int("GAMESMAN_STORE_PREFETCH_THREADS",
                _DEFAULT_PREFETCH_THREADS),
        env_bool("GAMESMAN_STORE_WRITEBEHIND", True),
    )
    with _DEFAULT_LOCK:
        if _DEFAULT is not None and _DEFAULT[0] == knobs:
            return _DEFAULT[1]
        old = _DEFAULT[1] if _DEFAULT is not None else None
        store = BlockStore(
            cache=TieredCache(max(1, knobs[0]) << 20,
                              registry=default_registry()),
            prefetch_threads=knobs[1],
            writebehind=knobs[2],
            registry=default_registry(),
        )
        _DEFAULT = (knobs, store)
    if old is not None:
        old.close()
    return store
