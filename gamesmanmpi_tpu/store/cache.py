"""TieredCache: the block store's unified host-RAM tier.

One byte-budget LRU per :class:`~gamesmanmpi_tpu.store.BlockStore`,
shared by every consumer that used to run a private LRU (the DbReader
hot-block cache, the checkpoint/spill loaders, backward edge reloads).
The mechanics are exactly ``compress/cache.BlockCache`` — byte-budget
LRU, lock-held bookkeeping only, decode-outside-the-lock — the only
difference is the metric family: a *store* cache's behavior is a
process-level observable (``gamesman_store_cache_*``), not a per-reader
one, so the series carries no per-reader labels by default (private
legacy caches — ``GAMESMAN_DB_CACHE_MB`` — pass a ``db=`` label to stay
separable).

The tier model (docs/ARCHITECTURE.md "Block store"): device HBM is the
solver's own ``GAMESMAN_DEVICE_STORE_MB`` budget, this cache is the
host-RAM tier (``GAMESMAN_STORE_CACHE_MB``), and the disk tier is the
sealed checkpoint/spill/DB files themselves — a miss here falls through
to a crc-verified sealed read, never to a wrong answer.
"""

from __future__ import annotations

from gamesmanmpi_tpu.compress.cache import BlockCache


class TieredCache(BlockCache):
    """Byte-budget LRU over decoded blocks/arrays, host-RAM tier."""

    def __init__(self, budget_bytes: int, *, registry=None, labels=None):
        instruments = None
        if registry is not None:
            lbl = dict(labels or {})
            instruments = (
                registry.counter(
                    "gamesman_store_cache_hits_total",
                    "store reads answered from the host-RAM tier",
                    **lbl,
                ),
                registry.counter(
                    "gamesman_store_cache_misses_total",
                    "store reads that fell through to the disk tier",
                    **lbl,
                ),
                registry.counter(
                    "gamesman_store_cache_evictions_total",
                    "entries evicted by the byte budget "
                    "(GAMESMAN_STORE_CACHE_MB)",
                    **lbl,
                ),
                registry.gauge(
                    "gamesman_store_cache_bytes",
                    "decoded bytes resident in the host-RAM tier",
                    **lbl,
                ),
            )
        super().__init__(int(budget_bytes), instruments=instruments)
