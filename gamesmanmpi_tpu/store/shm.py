"""Cross-worker shared decoded-block cache (ISSUE 18 tentpole).

Fork-mode fleet workers each keep a *private* decoded-block cache
(``DbReader``'s per-process ``BlockStore``), so N workers decode the
same hot block N times — once per process — even though the decoded
bytes are identical. This module puts the decoded (keys, cells) pairs
in one ``multiprocessing.shared_memory`` segment per host so a block
any worker decoded is a memcpy for every sibling, including workers
respawned after a crash (they re-attach by name and inherit the warm
set).

Design — correctness first, and "a stale slot is a miss, never a wrong
answer":

* **Direct-mapped slot directory.** The segment is a header page, an
  array of fixed-layout slot metadata records, and a data region of
  ``nslots`` fixed-size payload slots. A block keyed by
  ``(st_dev, st_ino, block_index)`` hashes (splitmix64) to exactly one
  slot; collisions overwrite (an eviction), which bounds memory by
  construction — there is no free list to leak and no LRU chain to
  corrupt across processes.
* **Epoch stamping.** Every slot records the DB *epoch* (the manifest
  sha, see ``DbReader.epoch``) it was filled under. A reader presents
  its own epoch on ``get``; any mismatch is a miss. A rolling reload
  that swaps the DB therefore invalidates the whole segment without
  touching it — and because the key includes the inode pair of the
  sealed keys file (fresh inodes on every overwrite swap, same trick
  ``BlockStore`` uses for its private tier), even an epoch collision
  cannot alias two different files' blocks.
* **Per-slot seqlock, lock-striped writers.** Writers serialize per
  slot stripe through ``fcntl.lockf`` on tempdir lock files (path
  locks, so fork- and exec-spawned workers interoperate — no inherited
  fd plumbing). Each slot carries a sequence number: odd while a write
  is in flight, bumped even when it lands. Readers take NO lock: read
  seq (odd -> miss), copy the payload, re-read seq — any change means
  a torn read and the reader falls back to decoding. Fleet reads are
  wait-free on the hot path.

The supervisor owns segment lifecycle (`create`/`unlink` — including a
fresh segment per reload generation); workers only ever `attach`.
Sizing comes from ``GAMESMAN_SHM_CACHE_MB`` (docs/CONFIG.md) resolved
by the supervisor into ``budget_bytes`` here.
"""

from __future__ import annotations

import fcntl
import hashlib
import os
import struct
import tempfile

import numpy as np

__all__ = ["ShmBlockCache"]

_MAGIC = b"GMSHM1\x00\x00"
_HEADER_BYTES = 4096
_HEADER_FMT = "<8sQQQ"  # magic, nslots, slot_bytes, nstripes
_M64 = (1 << 64) - 1

#: Slot metadata: the seqlock word, the block identity (device, inode,
#: block index), the epoch words, and the payload shape. Fixed layout
#: (explicit little-endian fields) so fork- and exec-spawned workers
#: agree byte-for-byte.
_META_DTYPE = np.dtype(
    [
        ("seq", "<u8"),
        ("dev", "<u8"),
        ("ino", "<u8"),
        ("block", "<u8"),
        ("epoch_hi", "<u8"),
        ("epoch_lo", "<u8"),
        ("keys_nbytes", "<u8"),
        ("cells_nbytes", "<u8"),
        ("keys_dtype", "<u1"),
        ("cells_dtype", "<u1"),
    ]
)

#: Payload dtype code table (code = index + 1; 0 = empty slot). Codes,
#: not dtype strings, keep the metadata record fixed-width.
_DTYPES = ("u1", "u2", "u4", "u8", "i1", "i2", "i4", "i8")


def _dtype_code(dtype) -> int:
    name = np.dtype(dtype).str.lstrip("<>|=")
    try:
        return _DTYPES.index(name) + 1
    except ValueError:
        return 0


def _mix64(x: int) -> int:
    """splitmix64 finalizer — a deterministic cross-process hash (the
    builtin ``hash`` is salted per-process for strings and must not
    decide slot placement)."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def _epoch_words(epoch: str) -> tuple:
    """The epoch string folded to two u64 slot-record words."""
    d = hashlib.blake2b(epoch.encode(), digest_size=16).digest()
    hi, lo = struct.unpack("<QQ", d)
    return hi, lo


class ShmBlockCache:
    """One host-wide decoded-block cache over a shared-memory segment.

    ``create`` (supervisor) or ``attach`` (worker), then ``get``/``put``
    decoded (keys, cells) pairs keyed by ``(dev, ino, block)`` under a
    DB epoch string. ``get`` returns ``None`` on any miss — absent,
    stale epoch, torn read, or foreign key — and never a wrong pair.
    """

    def __init__(self, shm, *, owner: bool, registry=None):
        self._shm = shm
        self._owner = owner
        magic, nslots, slot_bytes, nstripes = struct.unpack_from(
            _HEADER_FMT, shm.buf, 0
        )
        if magic != _MAGIC:
            raise ValueError(
                f"shm segment {shm.name!r} is not a GMSHM1 block cache"
            )
        self.nslots = int(nslots)
        self.slot_bytes = int(slot_bytes)
        self._nstripes = int(nstripes)
        meta_off = _HEADER_BYTES
        data_off = meta_off + self.nslots * _META_DTYPE.itemsize
        self._meta = np.frombuffer(
            shm.buf, dtype=_META_DTYPE, count=self.nslots, offset=meta_off
        )
        self._data = np.frombuffer(
            shm.buf, dtype=np.uint8,
            count=self.nslots * self.slot_bytes, offset=data_off,
        ).reshape(self.nslots, self.slot_bytes)
        self._lock_fds: dict = {}
        self._epoch_memo: dict = {}
        self._counts = {"hits": 0, "misses": 0, "stores": 0,
                        "evictions": 0}
        if registry is not None:
            self._m_hits = registry.counter(
                "gamesman_shm_hits_total",
                "decoded-block reads served from the cross-worker "
                "shared-memory cache",
            )
            self._m_misses = registry.counter(
                "gamesman_shm_misses_total",
                "shared-memory cache probes that fell through to a "
                "real block decode (absent, stale epoch, or torn slot)",
            )
            self._m_stores = registry.counter(
                "gamesman_shm_stores_total",
                "decoded blocks published into the shared-memory cache",
            )
            self._m_evictions = registry.counter(
                "gamesman_shm_evictions_total",
                "shared-memory slots overwritten while holding a "
                "different live block (direct-mapped collision)",
            )
            registry.gauge(
                "gamesman_shm_bytes",
                "total size of the attached shared decoded-block "
                "cache segment",
            ).set(float(shm.size))
        else:
            self._m_hits = self._m_misses = None
            self._m_stores = self._m_evictions = None

    # -- lifecycle ----------------------------------------------------

    @classmethod
    def create(cls, name: str, *, slot_bytes: int, budget_bytes: int,
               nstripes: int = 16, registry=None) -> "ShmBlockCache":
        """Supervisor-side: size, create and format a fresh segment.

        ``slot_bytes`` is the payload capacity per slot (the largest
        decoded (keys, cells) pair the fleet's DBs can produce);
        ``budget_bytes`` bounds the whole segment. Raises ``ValueError``
        when the budget cannot hold even one slot.
        """
        from multiprocessing import shared_memory

        slot_bytes = int(slot_bytes)
        per_slot = slot_bytes + _META_DTYPE.itemsize
        nslots = int(max(0, budget_bytes - _HEADER_BYTES) // per_slot)
        if nslots < 1:
            raise ValueError(
                f"shm budget {budget_bytes}B cannot hold one "
                f"{slot_bytes}B block slot"
            )
        size = _HEADER_BYTES + nslots * _META_DTYPE.itemsize \
            + nslots * slot_bytes
        shm = shared_memory.SharedMemory(name=name, create=True, size=size)
        # Fresh POSIX segments are zero-filled: every slot starts with
        # seq=0/dtype=0, i.e. empty. Only the header needs writing.
        struct.pack_into(_HEADER_FMT, shm.buf, 0, _MAGIC, nslots,
                         slot_bytes, int(max(1, min(nstripes, nslots))))
        return cls(shm, owner=True, registry=registry)

    @classmethod
    def attach(cls, name: str, registry=None) -> "ShmBlockCache":
        """Worker-side: attach to a supervisor-created segment by name."""
        from multiprocessing import shared_memory

        # Python < 3.13 registers ATTACHED segments with the resource
        # tracker too, and an exec-spawned worker gets its own tracker —
        # which would unlink the segment from under the whole fleet the
        # first time that worker exits. Suppress registration for the
        # attach (SharedMemory(track=False) is 3.13+): lifecycle belongs
        # to the supervisor, which created — and will unlink — the
        # segment under ITS tracker.
        from multiprocessing import resource_tracker

        orig = resource_tracker.register
        resource_tracker.register = lambda name_, rtype: None
        try:
            shm = shared_memory.SharedMemory(name=name)
        finally:
            resource_tracker.register = orig
        return cls(shm, owner=False, registry=registry)

    @property
    def name(self) -> str:
        return self._shm.name

    def close(self) -> None:
        """Drop this process's mapping (the segment itself survives)."""
        self._meta = None
        self._data = None
        for fd in self._lock_fds.values():
            try:
                os.close(fd)
            except OSError:
                pass
        self._lock_fds = {}
        try:
            self._shm.close()
        except (OSError, BufferError):
            pass

    def __del__(self):
        # Drop the numpy views BEFORE SharedMemory.__del__ runs: its
        # mmap close raises BufferError while exported views are alive
        # (interpreter-shutdown noise in every fleet worker otherwise).
        try:
            self.close()
        except Exception:
            pass

    def unlink(self) -> None:
        """Supervisor-side: close and destroy the segment + lock files."""
        name = self._shm.name
        self.close()
        try:
            self._shm.unlink()
        except (FileNotFoundError, OSError):
            pass
        for stripe in range(self._nstripes):
            try:
                os.unlink(self._lock_path(name, stripe))
            except OSError:
                pass

    # -- internals ----------------------------------------------------

    @staticmethod
    def _lock_path(name: str, stripe: int) -> str:
        return os.path.join(
            tempfile.gettempdir(), f"gamesman-{name}.s{stripe}.lock"
        )

    def _stripe_fd(self, stripe: int) -> int:
        fd = self._lock_fds.get(stripe)
        if fd is None:
            fd = os.open(
                self._lock_path(self._shm.name, stripe),
                os.O_CREAT | os.O_RDWR, 0o600,
            )
            self._lock_fds[stripe] = fd
        return fd

    def _slot_of(self, dev: int, ino: int, block: int) -> int:
        h = _mix64(_mix64(_mix64(dev & _M64) ^ (ino & _M64))
                   ^ (block & _M64))
        return h % self.nslots

    def _epoch(self, epoch: str) -> tuple:
        words = self._epoch_memo.get(epoch)
        if words is None:
            words = _epoch_words(epoch)
            if len(self._epoch_memo) > 8:  # reloads are rare; stay tiny
                self._epoch_memo.clear()
            self._epoch_memo[epoch] = words
        return words

    def _count(self, what: str, inst, n: int = 1) -> None:
        self._counts[what] += n
        if inst is not None:
            inst.inc(n)

    # -- hot path -----------------------------------------------------

    def get(self, key: tuple, epoch: str):
        """Wait-free probe: -> (keys, cells) arrays or None on miss."""
        dev, ino, block = key
        slot = self._slot_of(int(dev), int(ino), int(block))
        meta = self._meta[slot]
        seq0 = int(meta["seq"])
        ehi, elo = self._epoch(epoch)
        if (
            seq0 & 1
            or int(meta["keys_dtype"]) == 0
            or int(meta["dev"]) != int(dev)
            or int(meta["ino"]) != int(ino)
            or int(meta["block"]) != int(block)
            or int(meta["epoch_hi"]) != ehi
            or int(meta["epoch_lo"]) != elo
        ):
            self._count("misses", self._m_misses)
            return None
        kb = int(meta["keys_nbytes"])
        cb = int(meta["cells_nbytes"])
        kcode = int(meta["keys_dtype"])
        ccode = int(meta["cells_dtype"])
        if (
            kb + cb > self.slot_bytes
            or not 1 <= kcode <= len(_DTYPES)
            or not 1 <= ccode <= len(_DTYPES)
        ):
            self._count("misses", self._m_misses)
            return None
        payload = bytes(self._data[slot, : kb + cb])  # the copy
        if int(self._meta[slot]["seq"]) != seq0:
            # A writer landed mid-copy: torn — fall back to decode.
            self._count("misses", self._m_misses)
            return None
        keys = np.frombuffer(payload, dtype="<" + _DTYPES[kcode - 1],
                             count=kb // np.dtype(_DTYPES[kcode - 1]).itemsize)
        cells = np.frombuffer(payload, dtype="<" + _DTYPES[ccode - 1],
                              offset=kb)
        self._count("hits", self._m_hits)
        return keys, cells

    def put(self, key: tuple, epoch: str, keys, cells) -> bool:
        """Publish a decoded pair; False when it cannot be cached
        (oversized payload, unsupported dtype, or already present)."""
        keys = np.ascontiguousarray(keys)
        cells = np.ascontiguousarray(cells)
        kcode, ccode = _dtype_code(keys.dtype), _dtype_code(cells.dtype)
        nbytes = keys.nbytes + cells.nbytes
        if nbytes > self.slot_bytes or not kcode or not ccode:
            return False
        dev, ino, block = (int(k) for k in key)
        slot = self._slot_of(dev, ino, block)
        ehi, elo = self._epoch(epoch)
        fd = self._stripe_fd(slot % self._nstripes)
        fcntl.lockf(fd, fcntl.LOCK_EX)
        try:
            meta = self._meta[slot]
            seq = int(meta["seq"])
            occupied = int(meta["keys_dtype"]) != 0 and not seq & 1
            if (
                occupied
                and int(meta["dev"]) == dev
                and int(meta["ino"]) == ino
                and int(meta["block"]) == block
                and int(meta["epoch_hi"]) == ehi
                and int(meta["epoch_lo"]) == elo
            ):
                return False  # a sibling already published this block
            if occupied:
                self._count("evictions", self._m_evictions)
            meta["seq"] = (seq + 1) & _M64  # odd: write in flight
            self._data[slot, : keys.nbytes] = np.frombuffer(
                keys.astype(keys.dtype.newbyteorder("<"), copy=False)
                .tobytes(), dtype=np.uint8,
            )
            self._data[slot, keys.nbytes: nbytes] = np.frombuffer(
                cells.astype(cells.dtype.newbyteorder("<"), copy=False)
                .tobytes(), dtype=np.uint8,
            )
            meta["dev"] = dev
            meta["ino"] = ino
            meta["block"] = block
            meta["epoch_hi"] = ehi
            meta["epoch_lo"] = elo
            meta["keys_nbytes"] = keys.nbytes
            meta["cells_nbytes"] = cells.nbytes
            meta["keys_dtype"] = kcode
            meta["cells_dtype"] = ccode
            meta["seq"] = (seq + 2) & _M64  # even: slot live
        finally:
            fcntl.lockf(fd, fcntl.LOCK_UN)
        self._count("stores", self._m_stores)
        return True

    def stats(self) -> dict:
        """This process's probe counters plus the segment geometry."""
        return dict(
            self._counts, nslots=self.nslots, slot_bytes=self.slot_bytes,
            segment_bytes=int(self._shm.size),
        )
