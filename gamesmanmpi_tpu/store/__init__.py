"""gamesmanmpi_tpu.store — the one async block-store engine.

Everything that moves bytes between RAM and disk in this repo goes
through here (ROADMAP item 2): sealed crc-verified reads
(:mod:`store.sealed`), a byte-budget host-RAM tier
(:class:`TieredCache`), and the prefetch/write-behind engine
(:class:`BlockStore`). Consumers: ``utils/checkpoint.py`` (npz
framing + seals), ``parallel/sharded.py`` (edge/frontier spill +
readahead hints), ``db/reader.py`` (decompress-on-probe serving),
``db/writer.py`` (export write-behind). See docs/ARCHITECTURE.md
"Block store".
"""

from gamesmanmpi_tpu.store.blockstore import (  # noqa: F401
    BlockStore,
    WriteTicket,
    default_store,
    file_key,
)
from gamesmanmpi_tpu.store.cache import TieredCache  # noqa: F401
from gamesmanmpi_tpu.store.shm import ShmBlockCache  # noqa: F401
from gamesmanmpi_tpu.store.sealed import (  # noqa: F401
    BLOCKS_META_MEMBER,
    BlockedNpzView,
    CorruptSealError,
    SealedBlockStream,
    TORN_SEAL_ERRORS,
    file_crc32,
    loadz,
    open_npy_mmap,
    read_npz_members,
    verify_crc,
)
