#!/usr/bin/env python
"""Launcher shim — the reference repo's entry point is solver_launcher.py at
the repo root (SURVEY.md §2.2); this is its counterpart, delegating to
gamesmanmpi_tpu.cli."""

import sys

from gamesmanmpi_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
